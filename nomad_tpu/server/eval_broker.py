"""Evaluation broker (reference nomad/eval_broker.go).

Leader-only priority-queue broker with at-least-once delivery: ack/nack
with nack-timeout redelivery, a delivery limit that shunts poison evals to
a failed queue, per-JobID dedup ("evaluations for a given job are not run
in parallel", structs.go:9535 — while one eval of a job is outstanding,
later ones wait in a per-job pending heap), and delayed evals (wait_until)
held in a time heap.
"""
from __future__ import annotations

import heapq
import itertools
from collections import deque
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..structs import Evaluation, new_id
from ..trace import TRACE

DEFAULT_NACK_TIMEOUT = 60.0
DEFAULT_DELIVERY_LIMIT = 3
FAILED_QUEUE = "_failed"

# exported once the server wires its Metrics handle in (the broker is
# constructed before telemetry): a delivery-exhausted eval parked in
# the failed queue is the zero-lost-evals SLO's only burn signal, so
# absence of the series must mean "nothing lost", not "not exported"
# — Server zero-registers the family at construction
BROKER_COUNTERS = ("broker.delivery_failures",)

# job-id separators that mark a parent's spawned children: a dispatch
# or periodic storm is hundreds of sibling jobs under one parent
_FAMILY_SEPARATORS = ("/dispatch-", "/periodic-")


def job_family(ev: Evaluation) -> Tuple[str, str]:
    """The (namespace, parent job id) an eval's job belongs to.

    Dispatch and periodic children (``parent/dispatch-x``,
    ``parent/periodic-ts``) collapse onto their parent, so a mass
    dispatch, a drain stopping hundreds of children, or a scale-up
    wave all read as ONE family — the unit the batch worker's storm
    detector coalesces into a single global assignment solve.  The
    broker's one-outstanding-eval-per-job rule is untouched: family
    members are sibling *jobs*, each with its own dedup key.

    An explicit ``family_hint`` on the eval overrides the job-id
    derivation: the heartbeat sweeper stamps every replan eval of one
    mass node-death wave with the wave's hint, so a 500-node rack
    death — evals across MANY unrelated jobs — still coalesces into
    one storm family (and one global assignment solve) instead of
    hundreds of per-job chunk-chain walks."""
    hint = getattr(ev, "family_hint", "")
    if hint:
        return (ev.namespace, hint)
    job_id = ev.job_id or ""
    for sep in _FAMILY_SEPARATORS:
        i = job_id.find(sep)
        if i >= 0:
            job_id = job_id[:i]
            break
    return (ev.namespace, job_id)


class _ReadyQueue:
    """Priority heap: highest priority first, then FIFO by create index."""

    def __init__(self) -> None:
        self.heap: List[Tuple[int, int, Evaluation]] = []
        self._counter = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(
            self.heap, (-ev.priority, next(self._counter), ev)
        )

    def pop(self) -> Optional[Evaluation]:
        if not self.heap:
            return None
        return heapq.heappop(self.heap)[2]

    def peek_priority(self) -> Optional[int]:
        if not self.heap:
            return None
        return -self.heap[0][0]

    def __len__(self) -> int:
        return len(self.heap)


class EvalBroker:
    def __init__(
        self,
        nack_timeout: float = DEFAULT_NACK_TIMEOUT,
        delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
    ) -> None:
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit
        self._lock = threading.Condition()
        self._enabled = False

        self._ready: Dict[str, _ReadyQueue] = {}
        # eval id -> (eval, token, monotonic redelivery deadline).
        # ONE sweeper thread redelivers expired deliveries — a
        # threading.Timer per dequeue is an OS thread per in-flight
        # eval, which under load is thousands of short-lived threads
        self._unack: Dict[str, Tuple[Evaluation, str, float]] = {}
        # (namespace, job_id) -> outstanding eval id
        self._job_evals: Dict[Tuple[str, str], str] = {}
        # (namespace, job_id) -> heap of waiting evals (priority desc,
        # create_index asc) -- reference eval_broker.go:117
        self._pending: Dict[Tuple[str, str], List] = {}
        self._pending_counter = itertools.count()
        # eval id -> monotonic instant it became READY (insertion
        # order == enqueue order, so the first entry is the oldest):
        # feeds oldest_pending_age(), the overload ladder's queueing-
        # delay signal.  Redelivered evals re-stamp — age measures
        # time-in-ready, not time-since-first-submit
        self._ready_ts: Dict[str, float] = {}
        # delayed evals: (wait_until, n, eval)
        self._delayed: List[Tuple[float, int, Evaluation]] = []
        self._delivery_count: Dict[str, int] = {}
        # eval id -> peer server address for leases granted over the
        # cluster transport (follower scheduling fan-out).  Remote
        # leases live in _unack like any other delivery — the same
        # nack-timeout sweeper reclaims a dead follower's leases —
        # this map only attributes them per server for the stats
        # surface and post-mortem accounting.
        self._remote_leases: Dict[str, str] = {}
        self._ticker: Optional[threading.Thread] = None
        self.ticks = 0
        # tiny event ring for post-mortem debugging (eval id prefix,
        # action, monotonic ts) — cheap, and invaluable when an eval
        # "disappears" between enqueue and ack
        self.events: "deque" = deque(maxlen=128)
        self.stats = {
            "total_ready": 0,
            "total_unacked": 0,
            "total_blocked": 0,
            "total_waiting": 0,
            "total_remote_unacked": 0,
            "delivery_failures": 0,
        }
        # the owning server's Metrics handle (set post-construction;
        # None on bare brokers in unit tests)
        self.metrics = None
        # happens-before sanitizer (NOMAD_TPU_TSAN=1)
        from ..tsan import maybe_instrument

        maybe_instrument(self, "EvalBroker")

    # ------------------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        import os

        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._flush_locked()
            self._lock.notify_all()
            if enabled:
                self._ensure_ticker_locked()

    def _ensure_ticker_locked(self) -> None:
        # the redelivery sweeper: expires unacked deliveries past
        # their nack deadline and promotes delayed evals.  Re-armed
        # from EVERY lease-taking path (set_enabled, dequeue,
        # drain_family), not just enable — a drained storm family's
        # shadow-heap members must never depend on the storm path
        # settling for their redelivery, even if the sweeper thread
        # died.  With NOMAD_TPU_BROKER_WATCHDOG=1 it also
        # notify_all()s every tick — a workaround for sandboxed
        # schedulers that park timed Condition waits far past their
        # timeout (a 5ms wait observed sleeping 10s+ with the GIL
        # free, no lock holder, and no clock step).
        if self._ticker is None or not self._ticker.is_alive():
            self._ticker = threading.Thread(
                target=self._tick, name="broker-sweeper", daemon=True
            )
            self._ticker.start()

    def _tick(self) -> None:
        import os

        watchdog = os.environ.get("NOMAD_TPU_BROKER_WATCHDOG") == "1"
        while True:
            time.sleep(0.05)
            expired: List[Tuple[str, str]] = []
            with self._lock:
                self.ticks += 1
                if not self._enabled and not self._unack:
                    self._ticker = None
                    return
                now = time.monotonic()
                expired = [
                    (eval_id, token)
                    for eval_id, (_ev, token, deadline) in (
                        self._unack.items()
                    )
                    if deadline <= now
                ]
                self._promote_delayed_locked()
                if watchdog:
                    self._lock.notify_all()
            for eval_id, token in expired:
                try:
                    self.nack(eval_id, token)
                except ValueError:
                    pass  # acked/nacked concurrently

    @property
    def enabled(self) -> bool:
        return self._enabled

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        # callers already hold self._lock (re-entry would be legal —
        # a bare Condition wraps an RLock — just pointless work);
        # set_enabled flushes mid-critical-section through this
        self._ready.clear()
        self._ready_ts.clear()
        # in-flight traces must not dangle as "in flight" forever in
        # /v1/traces after a leadership revoke: every unacked delivery
        # dies with this flush, so settle its trace with an explicit
        # `revoked` outcome (the next leadership's redelivery begins a
        # fresh generation)
        for eval_id in self._unack:
            TRACE.finish(eval_id, "revoked")
        self._unack.clear()
        self._job_evals.clear()
        self._pending.clear()
        self._delayed.clear()
        self._delivery_count.clear()
        # the stats must follow the queues they describe: a stale
        # total_blocked after a flush pinned pending_depth() above
        # the overload threshold forever (mode never recovered), and
        # a stale total_unacked would wedge drain_to_idle
        self.stats["total_ready"] = 0
        self.stats["total_unacked"] = 0
        self.stats["total_blocked"] = 0
        self.stats["total_waiting"] = 0
        # remote leases die with the flush like every other token: a
        # follower's next ack/nack gets a token mismatch and the
        # next leader's restore_evals re-enqueues the evals
        self._remote_leases.clear()
        self.stats["total_remote_unacked"] = 0

    # ------------------------------------------------------------------

    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev, ev.type)
            self._lock.notify_all()

    def enqueue_all(self, evals: List[Evaluation]) -> None:
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev, ev.type)
            self._lock.notify_all()

    def _enqueue_locked(self, ev: Evaluation, queue: str) -> None:
        self.events.append((time.monotonic(), "enq", ev.id[:6], queue))
        if not self._enabled:
            return
        if ev.id in self._unack or any(
            ev.id == q_ev.id
            for q in self._ready.values()
            for _, _, q_ev in q.heap
        ):
            return
        if ev.wait_until and ev.wait_until > time.time():
            heapq.heappush(
                self._delayed,
                (ev.wait_until, next(self._pending_counter), ev),
            )
            self.stats["total_waiting"] += 1
            return
        job_key = (ev.namespace, ev.job_id)
        if queue != FAILED_QUEUE and ev.job_id:
            outstanding = self._job_evals.get(job_key)
            if outstanding and outstanding != ev.id:
                heapq.heappush(
                    self._pending.setdefault(job_key, []),
                    (-ev.priority, next(self._pending_counter), ev),
                )
                self.stats["total_blocked"] += 1
                return
            self._job_evals[job_key] = ev.id
        self._ready.setdefault(queue, _ReadyQueue()).push(ev)
        if queue != FAILED_QUEUE:
            self._ready_ts[ev.id] = time.monotonic()
        self.stats["total_ready"] += 1

    # ------------------------------------------------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[Evaluation], str]:
        """Blocking dequeue across the given scheduler queues; returns
        (eval, token) or (None, "") on timeout/disable."""
        deadline = time.monotonic() + timeout if timeout is not None else None
        with self._lock:
            while True:
                self._promote_delayed_locked()
                ev = self._pop_ready_locked(schedulers)
                if ev is not None:
                    token = new_id()
                    self._unack[ev.id] = (
                        ev, token, time.monotonic() + self.nack_timeout,
                    )
                    self._ensure_ticker_locked()
                    self.stats["total_unacked"] += 1
                    self.events.append((time.monotonic(), "deq", ev.id[:6], token[:6]))
                    # flight recorder: the dequeue is the trace root —
                    # every downstream span (pipeline stages, replay,
                    # plan apply, store commit) attaches to it by
                    # eval id
                    TRACE.begin(
                        ev.id,
                        queue=ev.type,
                        priority=ev.priority,
                        namespace=ev.namespace,
                        job_id=ev.job_id,
                        triggered_by=ev.triggered_by,
                    )
                    return ev, token
                if not self._enabled:
                    return None, ""
                wait = 0.05
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, ""
                    wait = min(wait, remaining)
                self._lock.wait(wait)

    def _pop_ready_locked(self, schedulers) -> Optional[Evaluation]:
        best_queue = None
        best_priority = None
        for name in schedulers:
            q = self._ready.get(name)
            if q is None or not len(q):
                continue
            p = q.peek_priority()
            if best_priority is None or p > best_priority:
                best_priority = p
                best_queue = q
        if best_queue is None:
            return None
        self.stats["total_ready"] -= 1
        ev = best_queue.pop()
        if ev is not None:
            self._ready_ts.pop(ev.id, None)
        return ev

    def drain_family(
        self,
        schedulers: List[str],
        family: Tuple[str, str],
        max_n: int,
        min_n: int = 1,
    ) -> List[Tuple[Evaluation, str]]:
        """Atomically dequeue the contiguous pop-order prefix of ready
        evals whose :func:`job_family` equals ``family`` — never
        leapfrogging an unrelated eval: the walk stops at the first
        ready eval of another family (or at ``max_n``).

        All-or-nothing below ``min_n``: when the prefix is shorter
        than ``min_n`` NOTHING is dequeued and ``[]`` is returned, so
        a storm probe that doesn't meet its trigger threshold leaves
        the queue byte-identical (re-pushing popped evals would mint
        fresh FIFO counters and reorder them within their priority
        class).  Each drained eval gets the full ``dequeue``
        bookkeeping — unack token, redelivery deadline, trace root —
        so ack/nack (and nack-timeout redelivery) work unchanged.

        This replaces the storm path's previous shape of N racing
        ``dequeue()`` calls, which interleaved with other consumers
        and could split one family's backlog across gulps."""
        with self._lock:
            self._promote_delayed_locked()
            # cheap rejection before any copying: when the pop-order
            # head is already another family the drainable prefix is
            # empty, and storm probes run at EVERY gulp boundary —
            # an O(ready backlog) shadow copy per dequeue would be
            # quadratic under mixed traffic
            head = None
            head_priority = None
            for name in schedulers:
                q = self._ready.get(name)
                if q is None or not len(q):
                    continue
                p = q.peek_priority()
                if head_priority is None or p > head_priority:
                    head_priority = p
                    head = q.heap[0][2]
            if head is None or job_family(head) != family:
                return []
            # phase 1: measure the prefix on shadow heaps (list copies
            # preserve the heap invariant) so a too-short prefix pops
            # nothing real
            shadows = {
                name: list(q.heap)
                for name, q in self._ready.items()
                if name in schedulers and len(q)
            }
            count = 0
            while count < max_n:
                best_name = None
                best_priority = None
                for name in schedulers:
                    heap = shadows.get(name)
                    if not heap:
                        continue
                    p = -heap[0][0]
                    if best_priority is None or p > best_priority:
                        best_priority = p
                        best_name = name
                if best_name is None:
                    break
                ev = heapq.heappop(shadows[best_name])[2]
                if job_family(ev) != family:
                    break
                count += 1
            if count < min_n:
                return []
            out: List[Tuple[Evaluation, str]] = []
            # the members' redelivery must not depend on the storm
            # path settling: the sweeper is (re)armed with the leases
            self._ensure_ticker_locked()
            for _ in range(count):
                ev = self._pop_ready_locked(schedulers)
                token = new_id()
                self._unack[ev.id] = (
                    ev, token, time.monotonic() + self.nack_timeout,
                )
                self.stats["total_unacked"] += 1
                self.events.append(
                    (time.monotonic(), "deq", ev.id[:6], token[:6])
                )
                TRACE.begin(
                    ev.id,
                    queue=ev.type,
                    priority=ev.priority,
                    namespace=ev.namespace,
                    job_id=ev.job_id,
                    triggered_by=ev.triggered_by,
                )
                out.append((ev, token))
            return out

    def dequeue_remote(
        self,
        schedulers: List[str],
        timeout: Optional[float] = None,
        max_n: int = 1,
        peer: str = "",
    ) -> List[Tuple[Evaluation, str]]:
        """Lease up to ``max_n`` ready evals for a REMOTE scheduling
        server (follower fan-out): one blocking dequeue, then a
        non-blocking sweep to fill the batch — one RPC round trip
        amortizes over the whole lease batch.

        Each lease gets the full ``dequeue`` bookkeeping (unack
        token, redelivery deadline, trace root), PLUS per-server
        attribution in ``_remote_leases`` so the stats surface can
        say which peer holds what.  The nack-timeout sweeper is
        re-armed HERE as well (the ``_ensure_ticker_locked`` pattern
        every lease-taking path follows): a follower that dies
        holding leases must never depend on any other path having
        armed the sweeper for its redelivery — a dead sweeper here
        would wedge ``drain_to_idle`` forever."""
        out: List[Tuple[Evaluation, str]] = []
        ev, token = self.dequeue(schedulers, timeout=timeout)
        if ev is None:
            return out
        out.append((ev, token))
        while len(out) < max_n:
            ev, token = self.dequeue(schedulers, timeout=0.0)
            if ev is None:
                break
            out.append((ev, token))
        self._track_remote(out, peer)
        return out

    def drain_family_remote(
        self,
        schedulers: List[str],
        family: Tuple[str, str],
        max_n: int,
        min_n: int = 1,
        peer: str = "",
    ) -> List[Tuple[Evaluation, str]]:
        """``drain_family`` on behalf of a remote server: the drain is
        atomic HERE, so a family gulp always lands whole on the one
        server that pulled the trigger eval — a storm solve is never
        split across followers."""
        out = self.drain_family(schedulers, family, max_n, min_n)
        self._track_remote(out, peer)
        return out

    def _track_remote(
        self, leases: List[Tuple[Evaluation, str]], peer: str
    ) -> None:
        if not leases:
            return
        with self._lock:
            # re-arm the redelivery sweeper from the remote path too:
            # these leases' redelivery must survive a follower death
            # even if every local lease-taking path has gone idle
            self._ensure_ticker_locked()
            for ev, token in leases:
                # the dequeue and this attribution are separate lock
                # acquisitions: a revoke flush (or a racing sweeper
                # nack) in between already invalidated the token, and
                # recording it anyway would leave a permanent orphan
                # in the per-peer accounting (nothing pops an entry
                # whose ack/nack can only raise).  Only a lease still
                # live under ITS token is attributed.
                entry = self._unack.get(ev.id)
                if entry is not None and entry[1] == token:
                    self._remote_leases[ev.id] = peer
            self.stats["total_remote_unacked"] = len(
                self._remote_leases
            )

    def remote_unacked_count(self) -> int:
        """Leases currently held by remote servers (subset of
        ``unacked_count``: every one also lives in ``_unack`` under
        the same nack-timeout)."""
        with self._lock:
            return len(self._remote_leases)

    def remote_lease_stats(self) -> Dict[str, int]:
        """Outstanding remote leases per peer server — which follower
        holds how much in-flight scheduling work right now."""
        with self._lock:
            out: Dict[str, int] = {}
            for peer in self._remote_leases.values():
                out[peer] = out.get(peer, 0) + 1
            return out

    def _promote_delayed_locked(self) -> None:
        now = time.time()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, ev = heapq.heappop(self._delayed)
            self.stats["total_waiting"] -= 1
            self._enqueue_locked(ev, ev.type)

    # ------------------------------------------------------------------

    def ack(self, eval_id: str, token: str) -> None:
        with self._lock:
            entry = self._unack.get(eval_id)
            if entry is None or entry[1] != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            ev, _, _deadline = entry
            del self._unack[eval_id]
            self.stats["total_unacked"] -= 1
            if self._remote_leases.pop(eval_id, None) is not None:
                self.stats["total_remote_unacked"] = len(
                    self._remote_leases
                )
            self.events.append((time.monotonic(), "ack", eval_id[:6], ""))
            TRACE.finish(eval_id, "ack")
            self._delivery_count.pop(eval_id, None)
            job_key = (ev.namespace, ev.job_id)
            if self._job_evals.get(job_key) == eval_id:
                del self._job_evals[job_key]
                pending = self._pending.get(job_key)
                if pending:
                    _, _, nxt = heapq.heappop(pending)
                    if not pending:
                        del self._pending[job_key]
                    self.stats["total_blocked"] -= 1
                    self._enqueue_locked(nxt, nxt.type)
            self._lock.notify_all()

    def nack(self, eval_id: str, token: str) -> None:
        with self._lock:
            entry = self._unack.get(eval_id)
            if entry is None or entry[1] != token:
                raise ValueError(f"token mismatch for eval {eval_id}")
            ev, _, _deadline = entry
            del self._unack[eval_id]
            self.stats["total_unacked"] -= 1
            if self._remote_leases.pop(eval_id, None) is not None:
                self.stats["total_remote_unacked"] = len(
                    self._remote_leases
                )
            self.events.append((time.monotonic(), "nack", eval_id[:6], ""))
            TRACE.finish(eval_id, "nack")
            job_key = (ev.namespace, ev.job_id)
            if self._job_evals.get(job_key) == eval_id:
                del self._job_evals[job_key]
            count = self._delivery_count.get(eval_id, 0) + 1
            self._delivery_count[eval_id] = count
            if count >= self.delivery_limit:
                self.stats["delivery_failures"] += 1
                if self.metrics is not None:
                    self.metrics.incr("broker.delivery_failures")
                self._enqueue_locked(ev, FAILED_QUEUE)
            else:
                self._enqueue_locked(ev, ev.type)
            self._lock.notify_all()

    # ------------------------------------------------------------------

    def outstanding(self, eval_id: str) -> Optional[str]:
        entry = self._unack.get(eval_id)
        return entry[1] if entry else None

    def unacked_count(self) -> int:
        """Outstanding deliveries: normal dequeues, drain_family
        shadow-heap members AND remote (fan-out RPC) leases — all
        live in ``_unack`` and are swept by the same nack-timeout
        redelivery, so a dead follower's leases count here until the
        sweeper reclaims them.  The leadership revoke path reads this
        just before the disable flush to report how much in-flight
        work the failover unacked."""
        with self._lock:
            return len(self._unack)

    def pending_depth(self) -> int:
        """Backlog the broker has accepted but no worker has started:
        ready evals (failed queue excluded — poison evals are parked,
        not pending) plus the per-job pending heaps.  The overload
        ladder's depth signal."""
        with self._lock:
            ready = sum(
                len(q)
                for name, q in self._ready.items()
                if name != FAILED_QUEUE
            )
            return ready + self.stats["total_blocked"]

    def oldest_pending_age(self) -> float:
        """Seconds the oldest READY eval has been waiting for a
        worker — the commit-wave lag the next accepted request will
        inherit before its eval even starts.  0.0 when nothing is
        ready.  O(1): ``_ready_ts`` is insertion-ordered and enqueue
        stamps are monotone, so the first entry is the oldest."""
        with self._lock:
            for ts in self._ready_ts.values():
                return max(0.0, time.monotonic() - ts)
            return 0.0

    def ready_count(self, schedulers=None) -> int:
        """Ready evals, optionally filtered to scheduler types — the
        BatchWorker's adaptive batch sizing reads this as the backlog
        signal (saturated: batch for throughput; keeping up: batch
        for latency)."""
        with self._lock:
            return sum(
                len(q)
                for name, q in self._ready.items()
                if schedulers is None or name in schedulers
            )

    def failed(self) -> List[Evaluation]:
        q = self._ready.get(FAILED_QUEUE)
        return [e for _, _, e in q.heap] if q else []
