"""Follower scheduling fan-out (reference nomad/worker.go on every
server + plan_queue.go serialization on the leader).

The reference's central scaling argument for the worker/plan-queue
split is that PLANNING scales horizontally — every server runs
scheduling workers against its own replicated state snapshot — while
COMMIT stays serialized on the leader's plan applier.  Until this
module, followers here only replicated and forwarded: every placement
was planned on the leader, so adding servers added commit durability
and zero scheduling throughput.

With ``NOMAD_TPU_FANOUT=1`` every follower runs the full TPU batch
pipeline (chunk chains, continuous admission, storm solves) against
its LOCAL replicated store and its own device:

* **Remote broker leases** — followers dequeue over the cluster
  transport (``broker_dequeue`` / ``broker_ack`` / ``broker_nack`` /
  ``broker_drain_family`` RPCs, batched up to
  ``NOMAD_TPU_FANOUT_LEASE_N`` leases per round trip).  Leases are
  stamped with the LEADER's leadership generation and tracked
  per-server on the leader's broker, where the existing nack-timeout
  sweeper reclaims a dead follower's leases like any other expired
  delivery.  The broker's one-outstanding-eval-per-job pending heaps
  are untouched, so same-job evals can never race across servers.
* **Local planning, serialized commit** — the follower waits
  ``snapshot_min_index(eval.modify_index)`` for its local FSM apply
  to catch up (the same fence the reference worker runs,
  worker.go:228), runs the unchanged assemble/launch/fetch/replay
  chunk chain — and whole-family storm solves, since
  ``drain_family`` gulps are atomic on the leader and so land on ONE
  server — on its local backend, then submits the plan through the
  ``submit_plan`` RPC into the leader's plan queue.  A partial
  commit's ``refresh_index`` is honored by waiting for LOCAL apply
  before the scheduler retries; stale-snapshot plans are exactly
  what ``evaluate_plan`` and the optimistic applier pipeline already
  handle.
* **Generation-fenced end to end** — follower plans carry the
  lease's leadership generation, so the replicated
  ``StaleLeadershipError`` fence (server/fsm.py) rejects work leased
  under a dead leadership on every replica deterministically.  A
  follower death mid-lease is just a nack-timeout redelivery; a
  leader death mid-submit is a structured not-leader response the
  worker converts to nack-for-redelivery.
"""
from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

from ..decisions import DECISIONS
from ..raft import NotLeaderError
from ..raft.transport import TransportError
from ..structs import Evaluation
from ..trace import TRACE
from .eval_broker import job_family
from .fsm import StaleLeadershipError

LOG = logging.getLogger("nomad_tpu.server.fanout")

# fan-out telemetry, zero-registered at Server construction (the
# `fanout-metrics` nomadlint rule enforces registry membership for
# every fanout.* emission across fanout.py / cluster.py / server.py)
FANOUT_COUNTERS = (
    # follower side
    "fanout.remote_dequeues",  # dequeue RPC round trips with >=1 lease
    "fanout.leases",  # leases received over RPC
    "fanout.acks",
    "fanout.nacks",
    "fanout.plans_submitted",  # plans submitted through the RPC
    "fanout.plan_refresh_waits",  # partial commits waited out locally
    "fanout.plan_not_leader",  # submits rejected by a leadership move
    "fanout.lease_gen_flips",  # leadership generation changed under us
    "fanout.stale_lease_drops",  # buffered leases dropped on a flip
    "fanout.apply_wait_timeouts",  # local FSM apply lagged past budget
    "fanout.segments_shipped",  # trace segments shipped to the leader
    # leader side
    "fanout.remote_leases_granted",
    "fanout.remote_plans",
)
FANOUT_GAUGES = (
    "fanout.workers",  # live fan-out workers on this (follower) server
    "fanout.lease_gen",  # leadership generation of the held leases
    "fanout.remote_unacked",  # leader: leases currently held by peers
)


def fanout_enabled() -> bool:
    return os.environ.get("NOMAD_TPU_FANOUT") == "1"


def fanout_workers() -> int:
    try:
        return max(
            1, int(os.environ.get("NOMAD_TPU_FANOUT_WORKERS", "1"))
        )
    except ValueError:
        return 1


def fanout_lease_n() -> int:
    try:
        return max(
            1, int(os.environ.get("NOMAD_TPU_FANOUT_LEASE_N", "8"))
        )
    except ValueError:
        return 8


def fanout_refresh_wait_s() -> float:
    try:
        return max(
            0.1,
            float(
                os.environ.get("NOMAD_TPU_FANOUT_REFRESH_WAIT_S", "5")
            ),
        )
    except ValueError:
        return 5.0


class RemoteBrokerClient:
    """The follower's view of the LEADER's eval broker.

    Implements exactly the broker surface the batch worker uses —
    ``dequeue`` / ``ack`` / ``nack`` / ``drain_family`` /
    ``ready_count`` — over the cluster transport.  Dequeues are
    batched: one RPC leases up to ``NOMAD_TPU_FANOUT_LEASE_N`` evals
    and the surplus is buffered locally, so the gulp-fill loop's
    per-eval dequeues are mostly buffer pops, not round trips.

    Every lease carries the leadership generation the leader stamped
    it with.  ``lease_gen`` is the newest stamp seen; buffered leases
    from an older generation are dropped (and best-effort nacked) the
    moment a newer stamp arrives — their tokens died with the old
    leadership's broker flush anyway.
    """

    def __init__(self, server) -> None:
        self._server = server  # the follower ClusterServer
        self._lock = threading.Lock()
        # buffered (ev, token, gen) leases not yet handed to a worker
        self._buffer: Deque[Tuple[Evaluation, str, int]] = deque()
        # newest leadership generation a lease RPC reported; the
        # follower view's `_leadership_gen` and every submitted
        # plan's `leader_gen` stamp read this
        self.lease_gen = 0
        # leader-reported ready backlog (piggybacked on lease RPCs):
        # feeds the worker's adaptive gulp/chunk sizing without a
        # dedicated RPC per sizing decision
        self._ready_hint = 0
        self.lease_n = fanout_lease_n()
        # decision-ledger dedup: lease absorption is per-RPC hot, so
        # the fanout_lease site ledgers only when the grant size
        # changes (or the generation flips) — the steady drip of
        # identical full grants is one record, not thousands
        self._last_lease_grant = -1

    # -- plumbing ------------------------------------------------------

    def _leader(self) -> Optional[str]:
        leader = self._server.raft.leader_hint()
        if leader == self._server.addr:
            return None  # we ARE the leader: fan-out must not self-RPC
        return leader

    def _rpc(self, method: str, payload: dict) -> dict:
        leader = self._leader()
        if leader is None:
            raise TransportError("no known leader")
        payload = dict(payload, server=self._server.addr)
        return self._server.transport.rpc(
            self._server.addr, leader, method, payload
        )

    def _metrics(self):
        return getattr(self._server, "metrics", None)

    def _count(self, kind: str, n: float = 1.0) -> None:
        metrics = self._metrics()
        if metrics is not None:
            metrics.incr(f"fanout.{kind}", n)

    def _absorb_leases(
        self, resp: dict, buffer: bool = True
    ) -> List[Tuple[Evaluation, str]]:
        """Fold one lease-granting RPC response into the local state:
        generation bookkeeping, ready-backlog hint, and (for plain
        dequeues) the shared lease buffer.  ``buffer=False`` returns
        the leases WITHOUT buffering — the storm path's drained
        family members belong to the draining worker alone and must
        never be visible to a sibling worker's buffer pops."""
        gen = int(resp.get("gen", 0))
        leases: List[Tuple[Evaluation, str]] = pickle.loads(
            resp["leases"]
        )
        # apply fence (see _lease_response): the eval objects the
        # leader enqueues carry modify_index=0, so the lease-time
        # leader index is the fence the follower's planning must wait
        # out — stamped on OUR unpickled copies as snapshot_index,
        # which both _await_local_apply and the sequential path's
        # snapshot_min_index already honor
        min_index = int(resp.get("min_index", 0))
        for ev, _token in leases:
            ev.snapshot_index = max(
                ev.snapshot_index or 0, min_index
            )
        # distributed trace propagation: each lease carries the
        # LEADER's trace context — open a local recording segment
        # under the leader's trace id so every pipeline span this
        # server records for the eval lands in the segment and ships
        # back on settle/submit (stale leases nacked below close
        # their segments through the same ship path)
        ctxs = resp.get("trace_ctx") or {}
        for ev, _token in leases:
            ctx = ctxs.get(ev.id)
            if ctx:
                TRACE.begin_segment(
                    ev.id, ctx, server_id=self._server.addr
                )
        stale: List[Tuple[Evaluation, str]] = []
        with self._lock:
            self._ready_hint = int(resp.get("ready", 0))
            if gen < self.lease_gen:
                # a DELAYED response from a deposed-but-not-yet-
                # stepped-down leader: its generation must never roll
                # ours back (that would nack valid newer-generation
                # buffered leases and trip the leadership fence on a
                # live chain).  The stale grants themselves go
                # straight back for redelivery below.
                stale.extend(leases)
                leases = []
            elif gen > self.lease_gen:
                if self.lease_gen:
                    self._count("lease_gen_flips")
                self.lease_gen = gen
                metrics = self._metrics()
                if metrics is not None:
                    metrics.set_gauge("fanout.lease_gen", float(gen))
                # buffered leases of an older generation died with
                # that leadership's broker flush: drop them here so a
                # worker can never start a chain on a dead token
                # (stale entries are always a prefix — stamps are
                # monotone and the buffer is append-ordered)
                while self._buffer and self._buffer[0][2] != gen:
                    b_ev, b_token, _g = self._buffer.popleft()
                    stale.append((b_ev, b_token))
            if buffer:
                for ev, token in leases:
                    self._buffer.append((ev, token, gen))
        if stale:
            self._count("stale_lease_drops", float(len(stale)))
        for ev, token in stale:
            try:
                self.nack(ev.id, token)
            except ValueError:
                pass
        if leases:
            self._count("remote_dequeues")
            self._count("leases", float(len(leases)))
        if DECISIONS.enabled and (
            len(leases) != self._last_lease_grant or stale
        ):
            self._last_lease_grant = len(leases)
            DECISIONS.record(
                "fanout_lease",
                f"granted={len(leases)}",
                inputs={
                    "requested": self.lease_n,
                    "ready_hint": self._ready_hint,
                    "lease_gen": self.lease_gen,
                    "stale_dropped": len(stale),
                    "buffered": buffer,
                },
                alternatives=[f"requested={self.lease_n}"],
                outcome="stale_drop" if stale else "absorbed",
                metrics=self._metrics(),
            )
        return leases

    def _pop_buffered(self) -> Tuple[Optional[Evaluation], str]:
        with self._lock:
            while self._buffer:
                ev, token, gen = self._buffer.popleft()
                if gen == self.lease_gen:
                    return ev, token
                # stale generation: token is already dead, drop it
            return None, ""

    # -- the broker surface the workers consume ------------------------

    def dequeue(
        self, schedulers: List[str], timeout: Optional[float] = None
    ) -> Tuple[Optional[Evaluation], str]:
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while True:
            ev, token = self._pop_buffered()
            if ev is not None:
                return ev, token
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
            rpc_timeout = min(
                0.1, remaining if remaining is not None else 0.1
            )
            t0 = time.monotonic()
            try:
                resp = self._rpc(
                    "broker_dequeue",
                    {
                        "schedulers": list(schedulers),
                        "timeout": max(0.0, rpc_timeout),
                        "n": self.lease_n,
                    },
                )
            except (TransportError, TimeoutError):
                resp = None
            if resp is None or resp.get("not_leader"):
                # leaderless interregnum (or a leader we can't see):
                # back off briefly and let the caller's timeout bound
                # the wait — the fan-out monitor tears workers down if
                # this server itself takes leadership
                if deadline is not None and (
                    time.monotonic() >= deadline
                ):
                    return None, ""
                time.sleep(0.02)
                continue
            leases = self._absorb_leases(resp)
            for l_ev, _tok in leases:
                # the dequeue RPC interval, attributed on each leased
                # eval's trace (the trace root was begun by the
                # leader-side broker dequeue)
                TRACE.add_span(
                    l_ev.id,
                    "fanout.remote_dequeue",
                    t0,
                    time.monotonic() - t0,
                    members=len(leases),
                    server=self._server.addr,
                )
            if not leases and deadline is not None and (
                time.monotonic() >= deadline
            ):
                return None, ""

    def _ship_segment(
        self, eval_id: str, close: bool
    ) -> Optional[dict]:
        """Export the eval's recorded trace segment for piggybacking
        on the settle/submit RPC (``close=True`` on settle retires the
        local buffer — the eval is leaving this server for good)."""
        segment = TRACE.export_segment(
            eval_id, self._server.addr, close=close
        )
        if segment is not None:
            self._count("segments_shipped")
        return segment

    def ack(self, eval_id: str, token: str) -> None:
        payload = {"eval_id": eval_id, "token": token}
        segment = self._ship_segment(eval_id, close=True)
        if segment is not None:
            payload["segment"] = segment
        try:
            resp = self._rpc("broker_ack", payload)
        except (TransportError, TimeoutError) as exc:
            # the lease holder is unreachable: the lease will expire
            # into a nack-timeout redelivery, and re-running the eval
            # is idempotent at the reconciler — same contract as a
            # leader-side crash between commit and ack
            raise ValueError(f"remote ack failed: {exc}") from exc
        if resp.get("not_leader") or resp.get("error"):
            raise ValueError(f"remote ack rejected for {eval_id}")
        self._count("acks")

    def nack(self, eval_id: str, token: str) -> None:
        payload = {"eval_id": eval_id, "token": token}
        segment = self._ship_segment(eval_id, close=True)
        if segment is not None:
            payload["segment"] = segment
        try:
            resp = self._rpc("broker_nack", payload)
        except (TransportError, TimeoutError) as exc:
            raise ValueError(f"remote nack failed: {exc}") from exc
        if resp.get("not_leader") or resp.get("error"):
            raise ValueError(f"remote nack rejected for {eval_id}")
        self._count("nacks")

    def drain_family(
        self,
        schedulers: List[str],
        family: Tuple[str, str],
        max_n: int,
        min_n: int = 1,
    ) -> List[Tuple[Evaluation, str]]:
        """The storm detector's atomic family drain, leased remotely.

        Batched dequeues mean this client's BUFFER may already hold
        the family's FIFO continuation — so the drain first claims
        the contiguous same-family prefix of the buffer, then (only
        if the buffer didn't hit a different-family boundary, which
        the no-leapfrog rule forbids jumping) extends it from the
        leader's broker, where ``drain_family`` is atomic.  Without
        the buffer phase a mass family would fragment: each lease
        batch would strand members in follower buffers below the
        storm trigger, and a coalescible 300-eval drain would decay
        into per-eval chunk chains.  All-or-nothing below ``min_n``
        is preserved — claimed buffer entries are re-prepended
        untouched, so a too-short prefix leaves the pop order
        byte-identical."""
        taken: List[Tuple[Evaluation, str, int]] = []
        stale: List[Tuple[Evaluation, str]] = []
        with self._lock:
            boundary = False
            while self._buffer and len(taken) < max_n:
                ev, token, gen = self._buffer[0]
                if gen != self.lease_gen:
                    # dead-generation stragglers: drop like
                    # _pop_buffered does (nacked below, best-effort)
                    self._buffer.popleft()
                    stale.append((ev, token))
                    continue
                if job_family(ev) != family:
                    boundary = True
                    break
                self._buffer.popleft()
                taken.append((ev, token, gen))

        def _restore() -> None:
            with self._lock:
                for entry in reversed(taken):
                    self._buffer.appendleft(entry)

        for ev, token in stale:
            try:
                self.nack(ev.id, token)
            except ValueError:
                pass
        out = [(ev, token) for ev, token, _gen in taken]
        remote: List[Tuple[Evaluation, str]] = []
        want_more = len(out) < max_n and not (
            boundary
            # a different-family eval buffered behind the prefix (or
            # still buffered at all) fences the walk exactly like the
            # broker's own no-leapfrog rule
            or self._buffered_count() > 0
        )
        if want_more:
            t0 = time.monotonic()
            try:
                resp = self._rpc(
                    "broker_drain_family",
                    {
                        "schedulers": list(schedulers),
                        "family": tuple(family),
                        "max_n": max_n - len(out),
                        "min_n": max(0, min_n - len(out)),
                    },
                )
            except (TransportError, TimeoutError):
                resp = {"not_leader": True}
            if not resp.get("not_leader"):
                # remote members bypass the shared buffer: the storm
                # path owns them exclusively (a sibling worker's pop
                # must never split a family gulp)
                remote = self._absorb_leases(resp, buffer=False)
                for ev, _tok in remote:
                    TRACE.add_span(
                        ev.id,
                        "fanout.remote_dequeue",
                        t0,
                        time.monotonic() - t0,
                        members=len(remote),
                        server=self._server.addr,
                    )
        total = out + remote
        if len(total) < min_n:
            # too short for the trigger: leave the pop order exactly
            # as it was (remote members can only exist here if the
            # leader's own all-or-nothing already passed its share,
            # so a short total means no remote members were taken)
            _restore()
            return []
        return total

    def _buffered_count(self) -> int:
        with self._lock:
            return sum(
                1
                for _ev, _tok, gen in self._buffer
                if gen == self.lease_gen
            )

    def ready_count(self, schedulers=None) -> int:
        """Leader-reported backlog hint + locally buffered leases —
        the adaptive gulp/chunk sizing signal, refreshed by every
        lease RPC instead of a dedicated round trip."""
        with self._lock:
            return self._ready_hint + len(self._buffer)

    def outstanding_buffered(self) -> List[Tuple[Evaluation, str]]:
        """Drain the local lease buffer (teardown path): the caller
        nacks these so a stopping worker never strands buffered
        leases until the nack timeout."""
        with self._lock:
            out = [(ev, token) for ev, token, _g in self._buffer]
            self._buffer.clear()
        return out


class _DonePending:
    """A ``PendingPlan``-shaped result for the synchronous remote
    submit: the RPC already round-tripped, so ``wait`` just hands the
    result back."""

    __slots__ = ("_result",)

    def __init__(self, result) -> None:
        self._result = result

    def wait(self, timeout: Optional[float] = None):
        return self._result


class RemotePlanQueue:
    """The follower's view of the LEADER's plan queue: ``enqueue``
    submits the plan over the ``submit_plan`` RPC (the leader
    enqueues it into its real plan queue and blocks for the
    serialized applier's verdict) and returns a pre-resolved pending.
    The plan and result pickle through the transport, so the follower
    and leader never alias one object graph."""

    def __init__(self, server, broker: RemoteBrokerClient) -> None:
        self._server = server
        self._broker = broker

    def enqueue(self, plan) -> _DonePending:
        payload = {"plan": pickle.dumps(plan)}
        eval_id = getattr(plan, "eval_id", None)
        if eval_id:
            # ship the spans closed so far (assemble/launch/fetch/
            # replay) with the submit — if this server dies between
            # submit and settle, the leader's stitched trace still
            # shows where the planning time went
            segment = self._broker._ship_segment(eval_id, close=False)
            if segment is not None:
                payload["segment"] = segment
        try:
            resp = self._broker._rpc("submit_plan", payload)
        except (TransportError, TimeoutError) as exc:
            # leader unreachable mid-submit: nothing committed that we
            # know of — surface as a leadership problem so the worker
            # nacks the eval for redelivery (an ambiguous commit is
            # idempotent to re-run at the reconciler)
            self._broker._count("plan_not_leader")
            raise NotLeaderError(None) from exc
        if resp.get("stale_leadership"):
            gen, fence = resp["stale_leadership"]
            self._broker._count("plan_not_leader")
            # definitive replicated verdict: the plan was produced
            # under a deposed leadership — never re-forwarded
            raise StaleLeadershipError(gen, fence)
        if resp.get("not_leader"):
            self._broker._count("plan_not_leader")
            raise NotLeaderError(resp.get("leader"))
        if resp.get("timeout"):
            raise TimeoutError("remote plan apply timed out")
        if resp.get("rejected"):
            return _DonePending(None)
        return _DonePending(pickle.loads(resp["result"]))


class _RemoteBlocked:
    """Blocked-eval tracking is a leader-only service: a follower
    worker's ``reblock_eval`` routes the (already replicated) eval to
    the leader, whose ``on_eval_update`` blocks or re-enqueues it."""

    def __init__(self, server) -> None:
        self._server = server

    def block(self, ev) -> None:
        # ClusterServer.on_eval_update forwards route_eval to the
        # leader (and swallows interregnum errors: the next
        # election's restore_evals re-tracks it from state)
        self._server.on_eval_update(ev)


class FollowerView:
    """What a fan-out worker sees as its ``server``: the follower
    ClusterServer with the broker/plan-queue/blocked surfaces
    replaced by their remote (leader-backed) clients, and the
    leadership fence re-derived from the LEASE generation.

    ``_leadership_gen`` is the generation the held leases were
    stamped with — the generation every submitted plan must carry so
    the replicated fence judges it by the leadership it ran under.
    ``_leader_established`` turns False the moment this server's own
    raft term advances past the lease generation (leadership
    definitively moved) or the fan-out manager stops — tripping the
    batch worker's `_check_leadership` fence exactly like a
    leader-side revoke."""

    def __init__(self, server, manager: "FanoutManager") -> None:
        self._server = server
        self._manager = manager
        self.broker = RemoteBrokerClient(server)
        self.plan_queue = RemotePlanQueue(server, self.broker)
        self.blocked = _RemoteBlocked(server)

    def __getattr__(self, name):
        return getattr(self._server, name)

    @property
    def _leadership_gen(self) -> int:
        return self.broker.lease_gen

    @property
    def _leader_established(self) -> bool:
        if not self._manager.active():
            return False
        gen = self.broker.lease_gen
        if gen <= 0:
            return False
        try:
            term = self._server.raft.stats()["term"]
        except Exception:  # noqa: BLE001 — fence fails safe
            return False
        return term <= gen


def _make_fanout_worker(view: FollowerView, seed=None):
    """Construct the follower-mode batch worker (lazy import: the
    batch worker pulls in the jax stack, which module import must not
    force on processes that never fan out)."""
    from .batch_worker import BatchWorker

    class FanoutBatchWorker(BatchWorker):
        """The full TPU batch pipeline, running on a FOLLOWER: local
        replicated state + local device for planning, remote leases
        and remote (serialized) plan commit."""

        # under NOMAD_TPU_FANOUT_MESH=1 this is the one worker class
        # allowed to bring up the device mesh (and head the pod) —
        # see BatchWorker._mesh_allowed
        _is_fanout_worker = True

        def __init__(self, server, **kwargs) -> None:
            super().__init__(server, **kwargs)
            self._refresh_wait_s = fanout_refresh_wait_s()

        def _count_fanout(self, kind: str) -> None:
            metrics = getattr(self.server, "metrics", None)
            if metrics is not None:
                metrics.incr(f"fanout.{kind}")

        def _await_local_apply(self, held) -> bool:
            """The follower analogue of worker.go:228's
            snapshot_min_index fence, hoisted to the gulp boundary:
            wait for the local FSM apply to reach every held eval's
            modify index before the batched pipeline simulates
            against local state.  On timeout every lease is nacked
            for redelivery (False) — planning from state older than
            the eval's trigger could re-place allocations the lagging
            snapshot doesn't show yet."""
            target = 0
            for ev, _token in held:
                target = max(
                    target,
                    ev.modify_index or 0,
                    ev.snapshot_index or 0,
                )
            if target <= self.store.latest_index():
                return True
            try:
                self.store.snapshot_min_index(
                    target, timeout=self._refresh_wait_s
                )
                return True
            except TimeoutError:
                self._count_fanout("apply_wait_timeouts")
                DECISIONS.record(
                    "fanout_nack",
                    "nack_redeliver",
                    inputs={
                        "held": len(held),
                        "target_index": target,
                        "local_index": self.store.latest_index(),
                        "wait_s": self._refresh_wait_s,
                        "leader_gen": self._leader_gen(),
                    },
                    alternatives=["keep_waiting"],
                    outcome="apply_wait_timeout",
                    trace_id=held[0][0].id if held else "",
                    metrics=getattr(self.server, "metrics", None),
                )
                for ev, token in held:
                    self._nack_quietly(ev, token)
                return False

        def _process_batch(self, batch):
            if not self._await_local_apply(batch):
                return []
            return super()._process_batch(batch)

        def _process_storm(self, members):
            if not self._await_local_apply(members):
                return []
            return super()._process_storm(members)

        def submit_plan(self, plan):
            """Worker.submit_plan with the remote commit protocol:
            the plan carries the LEASE generation, the enqueue is the
            synchronous ``submit_plan`` RPC, and both the partial-
            commit ``refresh_index`` and our own full commit's
            ``alloc_index`` are honored by waiting for LOCAL apply —
            the next chain member must see this plan's allocations
            in follower state, or its conflict fences would demote
            every subsequent wave member to a serial re-replay."""
            import time as _time

            if getattr(plan, "leader_gen", None) is None:
                plan.leader_gen = self._leader_gen()
            plan.snapshot_index = self.store.latest_index()
            t0 = _time.monotonic()
            try:
                pending = self.server.plan_queue.enqueue(plan)
                result = pending.wait(timeout=10.0)
                if result is None:
                    raise RuntimeError("plan rejected")
                self._count_fanout("plans_submitted")
                if result.refresh_index:
                    self._count_fanout("plan_refresh_waits")
                    DECISIONS.record(
                        "fanout_nack",
                        "refresh_wait",
                        inputs={
                            "refresh_index": result.refresh_index,
                            "local_index": self.store.latest_index(),
                            "wait_s": self._refresh_wait_s,
                            "leader_gen": self._leader_gen(),
                        },
                        alternatives=["plan_on_stale_snapshot"],
                        outcome="partial_commit",
                        trace_id=plan.eval_id or "",
                        metrics=getattr(
                            self.server, "metrics", None
                        ),
                    )
                    snap = self.store.snapshot_min_index(
                        result.refresh_index,
                        timeout=self._refresh_wait_s,
                    )
                    return result, snap
                if result.alloc_index:
                    # best-effort catch-up to our own commit; a
                    # lagging apply only costs conflict-fence
                    # fallbacks, never correctness (the leader's
                    # evaluate_plan is the serialization point)
                    self.store.wait_for_index(
                        result.alloc_index,
                        timeout=self._refresh_wait_s,
                    )
                return result, None
            finally:
                # commit-plane wait accounting (Worker.plan_wait_s):
                # the remote round trip + local-apply catch-up is
                # serialized-commit latency, not planning work
                dt = _time.monotonic() - t0
                self.plan_wait_s += dt
                if plan.eval_id:
                    TRACE.add_span(
                        plan.eval_id, "fanout.plan_submit", t0, dt
                    )

    return FanoutBatchWorker(view, seed=seed)


class FanoutManager:
    """Owns the fan-out worker fleet on one ClusterServer: a monitor
    thread watches the raft role and runs ``NOMAD_TPU_FANOUT_WORKERS``
    follower-mode batch workers exactly while this server is a
    follower with a known leader.  Taking leadership (or stopping)
    tears them down — the leader's own workers take over, and the
    follower view's ``_leader_established`` goes False so in-flight
    chains abort through the leadership fence."""

    def __init__(self, server, seed=None) -> None:
        self.server = server
        self.seed = seed
        self.enabled = fanout_enabled()
        self.view: Optional[FollowerView] = None
        self.workers: List[object] = []
        self._active = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def active(self) -> bool:
        return self._active

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._monitor, name="fanout-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
        self._thread = None
        self._stop_workers(dispose=True)

    # -- monitor loop --------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.is_set():
            try:
                self._reconcile()
            except Exception:  # noqa: BLE001 — the monitor must
                # survive any single pass; a dead monitor would
                # silently freeze the fan-out fleet in its last shape
                LOG.exception("fanout reconcile failed")
            self._stop.wait(0.05)
        self._stop_workers()

    def _reconcile(self) -> None:
        srv = self.server
        if not srv._running or srv.is_leader():
            self._stop_workers()
            return
        if srv.raft.leader_hint() is None:
            # leaderless interregnum: running workers idle on failed
            # dequeues (cheap) and resume the moment a leader exists;
            # none are STARTED until one is known
            return
        self._ensure_workers()

    def _ensure_workers(self) -> None:
        with self._lock:
            if self._active and all(
                w._thread is not None and w._thread.is_alive()
                for w in self.workers
            ):
                return
            if self.view is None:
                self.view = FollowerView(self.server, self)
            self._active = True
            if not self.workers:
                self.workers = [
                    _make_fanout_worker(self.view, seed=self.seed)
                    for _ in range(fanout_workers())
                ]
            for worker in self.workers:
                if worker._thread is None or not (
                    worker._thread.is_alive()
                ):
                    worker.start()
            metrics = getattr(self.server, "metrics", None)
            if metrics is not None:
                metrics.set_gauge(
                    "fanout.workers", float(len(self.workers))
                )

    def _stop_workers(self, dispose: bool = False) -> None:
        """Tear the fleet down.  ``dispose=False`` (a leadership
        change) PARKS the workers rather than discarding them: their
        device mirrors — and, on a pod head, the mesh peers' mirror
        shards, which a discarded worker could never rebuild (the old
        pod service still owns the port) — stay resident, so
        re-establishing the fleet catches up in O(dirty rows) deltas
        instead of a full-world resync.  A parked worker's mirrors
        are marked dirty exactly like ``_on_device_transition``: an
        abandoned in-flight launch may still be reading them, so the
        catch-up sync must re-upload rather than donate the buffers
        out from under it — without this, a re-established fleet
        plans against a mirror whose buffers a straggler consumed.
        ``dispose=True`` (manager shutdown) additionally releases the
        workers and their pod service."""
        with self._lock:
            if not self._active and not self.workers:
                return
            self._active = False
            if dispose:
                workers, self.workers = self.workers, []
            else:
                workers = list(self.workers)
            view = self.view
        for worker in workers:
            if dispose and hasattr(worker, "dispose"):
                worker.dispose()
            else:
                worker.stop()
            mark = getattr(worker, "_mark_mirror_dirty", None)
            if mark is not None:
                mark()
        # buffered (undelivered) leases must not sit out the nack
        # timeout: hand them straight back for redelivery
        if view is not None:
            for ev, token in view.broker.outstanding_buffered():
                try:
                    view.broker.nack(ev.id, token)
                except ValueError:
                    pass
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.set_gauge("fanout.workers", 0.0)
