"""Replicated state-machine command layer (reference nomad/fsm.go).

Every control-plane write is a typed command applied through this
dispatch — when the server runs replicated, commands arrive through the
raft log and every server applies the same stream to its local
StateStore/ACLStore (reference fsm.go:180 Apply over ~40 MessageTypes);
in single-process mode the Server applies them directly.  Commands are
pickled (kind, args) tuples: self-describing like the reference's
msgpack-encoded requests, and the round-trip gives each replica its own
object graph (no cross-server aliasing).

Eval routing (broker enqueue on EvalUpdate, fsm.go:715) deliberately
stays OUT of the FSM here: the API layer routes evals on the leader
after the apply returns, and a newly-elected leader recovers pending
evals from state via restore_evals (reference leader.go:352) — same
at-least-once outcome without followers needing a broker.
"""
from __future__ import annotations

import gzip
import pickle
from collections import OrderedDict
from typing import Optional, Tuple

from ..raft import NotLeaderError
from ..state.store import StateStore
from ..trace import TRACE

SNAPSHOT_VERSION = 1

# applied command ids retained for at-least-once forward dedup; far
# above any plausible in-flight retry window, bounded so the FSM's
# memory stays O(1) under sustained traffic
CMD_DEDUP_MAX = 8192


class StaleLeadershipError(NotLeaderError):
    """A command stamped by a deposed leadership generation reached the
    FSM after a newer leader's barrier committed.  Subclasses
    NotLeaderError so the worker layer's nack-for-redelivery handling
    covers it, but it is DEFINITIVE: the forwarding retry loop must
    propagate it, never re-forward (the rejection is replicated — every
    FSM applies the same verdict)."""

    def __init__(self, gen: int, fence: int) -> None:
        Exception.__init__(
            self,
            f"command from deposed leadership gen {gen} "
            f"(fence is gen {fence})",
        )
        self.leader = None
        self.gen = gen
        self.fence = fence


def encode_command(
    kind: str, args: tuple, cmd_id: Optional[str] = None
) -> bytes:
    """Commands travel as (kind, args, cmd_id) — cmd_id is the
    client-supplied idempotency key: a forward retry after a lost ack
    re-proposes the SAME id, and the FSM's dedup table returns the
    first apply's result instead of mutating twice."""
    return pickle.dumps(
        (kind, args, cmd_id), protocol=pickle.HIGHEST_PROTOCOL
    )


def normalize_plan_result(result):
    """Wire-efficient form of a PlanResult: stopped/preempted allocs
    shrink to AllocationDiffs — an id plus the mutated status fields —
    instead of full Job-bearing Allocation graphs (reference
    plan_apply.go:324-344 normalizePlan + Plan.NormalizeAllocations).
    Placements stay whole: they carry state replicas don't have yet."""
    from ..structs import AllocationDiff, PlanResult

    if result.normalized:
        return result

    def diffs(allocs):
        return [
            AllocationDiff(
                id=a.id,
                desired_status=a.desired_status,
                desired_description=a.desired_description,
                client_status=a.client_status,
                followup_eval_id=a.followup_eval_id,
                preempted_by_allocation=a.preempted_by_allocation,
            )
            for a in allocs
        ]

    return PlanResult(
        node_update={
            nid: diffs(allocs)
            for nid, allocs in result.node_update.items()
        },
        node_allocation=result.node_allocation,
        node_preemptions={
            nid: diffs(allocs)
            for nid, allocs in result.node_preemptions.items()
        },
        deployment=result.deployment,
        deployment_updates=result.deployment_updates,
        refresh_index=result.refresh_index,
        alloc_index=result.alloc_index,
        normalized=True,
    )


def denormalize_plan_result(store: StateStore, result):
    """Reconstitute full stop/preemption allocs from AllocationDiffs
    against the replica's own state (reference fsm.go ApplyPlanResults
    -> state DenormalizeAllocationSlice).  Diffs whose alloc no longer
    exists are dropped — the stop already won."""
    from dataclasses import replace

    from ..structs import PlanResult

    if not result.normalized:
        return result

    def expand(diff_lists):
        out = {}
        for nid, diff_list in diff_lists.items():
            allocs = []
            for d in diff_list:
                existing = store.alloc_by_id(d.id)
                if existing is None:
                    continue
                alloc = replace(existing)
                alloc.desired_status = d.desired_status
                alloc.desired_description = d.desired_description
                if d.client_status:
                    alloc.client_status = d.client_status
                if d.followup_eval_id:
                    alloc.followup_eval_id = d.followup_eval_id
                if d.preempted_by_allocation:
                    alloc.preempted_by_allocation = (
                        d.preempted_by_allocation
                    )
                allocs.append(alloc)
            if allocs:
                out[nid] = allocs
        return out

    return PlanResult(
        node_update=expand(result.node_update),
        node_allocation=result.node_allocation,
        node_preemptions=expand(result.node_preemptions),
        deployment=result.deployment,
        deployment_updates=result.deployment_updates,
        refresh_index=result.refresh_index,
        alloc_index=result.alloc_index,
        normalized=False,
    )


def decode_command(
    raw: bytes,
) -> Tuple[str, tuple, Optional[str]]:
    """(kind, args, cmd_id) of a command; tolerant of the pre-cmd-id
    2-tuple wire form (cmd_id None) so mixed-version logs still
    apply."""
    loaded = pickle.loads(raw)
    return loaded[0], loaded[1], (
        loaded[2] if len(loaded) > 2 else None
    )


def state_payload(store: StateStore, acls) -> dict:
    """Capture the full replicated state (reference fsm.go Snapshot:
    every table is persisted)."""
    with store._lock:
        payload = {
            "version": SNAPSHOT_VERSION,
            "index": store.latest_index(),
            "table_indexes": dict(store._table_index),
            "nodes": list(store.nodes.values()),
            "jobs": list(store.jobs.values()),
            "job_versions": {
                k: list(v) for k, v in store.job_versions.items()
            },
            "allocs": list(store.allocs.values()),
            "evals": list(store.evals.values()),
            "deployments": list(store.deployments.values()),
            "scheduler_config": store.scheduler_config,
            "autopilot_config": store.autopilot_config,
            "csi_volumes": list(store.csi_volumes.values()),
            "namespaces": list(store.namespaces.values()),
            "scaling_policies": list(store.scaling_policies.values()),
            "scaling_events": {
                k: {g: list(evs) for g, evs in v.items()}
                for k, v in store.scaling_events.items()
            },
        }
        # bigworld allocation ballast (array-backed seeded usage) is
        # replicated state: persist it keyed by node id so restore can
        # re-row it against the rebuilt node table
        if store._seed_usage is not None:
            base = store._seed_usage
            nz = (base[0] + base[1] + base[2]).nonzero()[0]
            ids = store.node_table.node_ids
            payload["seed_usage"] = {
                ids[row]: (
                    float(base[0][row]),
                    float(base[1][row]),
                    float(base[2][row]),
                )
                for row in nz.tolist()
                if ids[row] is not None
            }
            payload["seed_alloc_count"] = store._seed_alloc_count
    if acls is not None:
        payload["acl_policies"] = list(acls.policies.values())
        payload["acl_tokens"] = list(acls.tokens_by_accessor.values())
        payload["acl_enabled"] = acls.enabled
    return payload


def install_payload(store: StateStore, acls, payload: dict) -> int:
    """Replace local state with a snapshot payload (reference fsm.go
    Restore).  Secondary indexes and the columnar node table are
    derived state and get rebuilt."""
    if payload.get("version") != SNAPSHOT_VERSION:
        raise ValueError(
            f"unsupported snapshot version {payload.get('version')}"
        )
    from ..state.node_table import NodeTable

    with store._lock:
        store.nodes.clear()
        store.jobs.clear()
        store.job_versions.clear()
        store.allocs.clear()
        store.evals.clear()
        store.deployments.clear()
        store._allocs_by_node.clear()
        store._allocs_by_job.clear()
        store._allocs_by_eval.clear()
        store._evals_by_job.clear()
        store._deployments_by_job.clear()
        # the columnar mirror is derived state: rebuild it from scratch
        # so rows/usage from pre-snapshot nodes can't survive
        store.node_table = NodeTable()

        for node in payload["nodes"]:
            store.nodes[node.id] = node
            store.node_table.upsert_node(node)
        for job in payload["jobs"]:
            store.jobs[(job.namespace, job.id)] = job
        for key, versions in payload["job_versions"].items():
            store.job_versions[key] = versions
        for alloc in payload["allocs"]:
            store.allocs[alloc.id] = alloc
            store._allocs_by_node[alloc.node_id].add(alloc.id)
            store._allocs_by_job[(alloc.namespace, alloc.job_id)].add(
                alloc.id
            )
            if alloc.eval_id:
                store._allocs_by_eval[alloc.eval_id].add(alloc.id)
        # recompute usage for every node (not just those with allocs in
        # the snapshot — a node whose allocs all stopped must read zero)
        # The port/device occupancy indexes are derived state too: clear
        # the pre-restore entries (phantom static-port occupancy would
        # skew the batch kernel's port_used0 columns) and rebuild them —
        # _refresh_port_index also repopulates node_table.device_used
        # from the restored live allocs.
        store._ports_live.clear()
        store._ports_by_node.clear()
        # re-row the seeded allocation ballast BEFORE the usage
        # recompute below — _live_usage_for_node reads it per node
        seed_usage = payload.get("seed_usage")
        if seed_usage:
            import numpy as np

            cap = store.node_table.capacity
            base = [np.zeros(cap, dtype=np.float64) for _ in range(3)]
            for nid, (c, m, d) in seed_usage.items():
                row = store.node_table.row_of.get(nid)
                if row is None:
                    continue
                base[0][row] = c
                base[1][row] = m
                base[2][row] = d
            store._seed_usage = base
            store._seed_alloc_count = payload.get(
                "seed_alloc_count", 0
            )
        else:
            store._seed_usage = None
            store._seed_alloc_count = 0
        for node_id in store.nodes:
            store.node_table.update_node_usage(
                node_id, store._live_usage_for_node(node_id)
            )
            store._refresh_port_index(node_id)
        for ev in payload["evals"]:
            store.evals[ev.id] = ev
            store._evals_by_job[(ev.namespace, ev.job_id)].add(ev.id)
        for d in payload["deployments"]:
            store.deployments[d.id] = d
            store._deployments_by_job[(d.namespace, d.job_id)].add(d.id)
        store.scheduler_config = payload["scheduler_config"]
        store.autopilot_config = payload.get("autopilot_config")
        store.csi_volumes.clear()
        for vol in payload.get("csi_volumes", ()):
            store.csi_volumes[(vol.namespace, vol.id)] = vol
        store.namespaces.clear()
        for ns in payload.get("namespaces", ()):
            store.namespaces[ns.name] = ns
        if "default" not in store.namespaces:
            from ..structs import Namespace

            store.namespaces["default"] = Namespace(
                name="default",
                description="Default shared namespace",
            )
        store.scaling_policies.clear()
        store._scaling_by_target.clear()
        store.scaling_events.clear()
        for pol in payload.get("scaling_policies", ()):
            store.scaling_policies[pol.id] = pol
            store._scaling_by_target[pol.target_tuple()] = pol.id
        for key, per_group in payload.get("scaling_events", {}).items():
            store.scaling_events[key] = {
                g: list(evs) for g, evs in per_group.items()
            }
        store._index = payload["index"]
        store._table_index.clear()
        store._table_index.update(payload.get("table_indexes", {}))
        store._watch_cond.notify_all()
        # delta-level consumers (service catalog) must resync: the
        # restore wrote the alloc table wholesale without per-alloc
        # notifications
        store._notify_alloc_watchers(None)

    if acls is not None and "acl_enabled" in payload:
        acls.enabled = payload["acl_enabled"]
        acls.policies.clear()
        acls.tokens_by_accessor.clear()
        acls.tokens_by_secret.clear()
        for policy in payload.get("acl_policies", ()):
            acls.upsert_policy(policy)
        for token in payload.get("acl_tokens", ()):
            acls.tokens_by_accessor[token.accessor_id] = token
            acls.tokens_by_secret[token.secret_id] = token
    return payload["index"]


class ServerFSM:
    """Applies committed commands to the local store (the raft FSM).

    Pure state mutation, deterministic from the command stream — every
    replica that applies the same log prefix holds identical state and
    identical modify indexes.
    """

    def __init__(self, store: StateStore, acls=None) -> None:
        self.store = store
        self.acls = acls
        # committed leadership fence: the newest leadership generation
        # whose barrier command reached this FSM.  Checked UNDER the
        # apply (not host-side) so a deposed leader's in-flight plan —
        # even one forwarded to the new leader — is rejected by every
        # replica deterministically.
        self.leadership_fence = 0
        # cmd_id -> result of successfully applied commands (forward
        # retries re-propose the same id; the dup returns the cached
        # result without mutating state).  Part of the snapshot so a
        # compaction can't resurrect a dup on one replica only.
        self._applied_cmds: "OrderedDict[str, object]" = OrderedDict()

    # raft FSM contract -------------------------------------------------

    def apply(self, raw: bytes):
        kind, args, cmd_id = decode_command(raw)
        if cmd_id is not None and cmd_id in self._applied_cmds:
            # at-least-once forward dedup: the first apply's result,
            # no second mutation.  Failures are NOT cached — handlers
            # are deterministic functions of state, so a re-applied
            # failed command fails identically on every replica.
            return self._applied_cmds[cmd_id]
        result = self.dispatch(kind, args)
        if cmd_id is not None:
            self._applied_cmds[cmd_id] = result
            while len(self._applied_cmds) > CMD_DEDUP_MAX:
                self._applied_cmds.popitem(last=False)
        return result

    def snapshot(self) -> bytes:
        payload = state_payload(self.store, self.acls)
        payload["leadership_fence"] = self.leadership_fence
        payload["cmd_dedup"] = list(self._applied_cmds.items())
        return gzip.compress(
            pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        )

    def restore(self, raw: bytes) -> None:
        payload = pickle.loads(gzip.decompress(raw))
        install_payload(self.store, self.acls, payload)
        self.leadership_fence = payload.get("leadership_fence", 0)
        self._applied_cmds = OrderedDict(payload.get("cmd_dedup", ()))

    # command dispatch (reference fsm.go:197-277) -----------------------

    def dispatch(self, kind: str, args: tuple):
        handler = getattr(self, f"_apply_{kind}", None)
        if handler is None:
            raise ValueError(f"unknown FSM command {kind!r}")
        return handler(*args)

    def _apply_upsert_node(self, node):
        return self.store.upsert_node(node)

    def _apply_seed_world(self, spec):
        """Deterministic synthetic-world expansion (bigworld): the log
        carries the tiny spec, every replica expands it to the same
        bulk-registered nodes + allocation ballast locally."""
        from ..loadgen.bigworld import seed_world

        return seed_world(self.store, spec)

    def _apply_delete_node(self, node_id):
        return self.store.delete_node(node_id)

    def _apply_update_node_status(self, node_id, status, now=None):
        return self.store.update_node_status(node_id, status, now)

    def _apply_update_node_statuses(
        self, node_ids, status, now=None, message=""
    ):
        # one mass node-death wave = one command = one atomic apply
        return self.store.update_node_statuses(
            node_ids, status, now, message
        )

    def _apply_update_node_eligibility(self, node_id, eligibility):
        return self.store.update_node_eligibility(node_id, eligibility)

    def _apply_update_node_drain(self, node_id, drain, strategy):
        return self.store.update_node_drain(node_id, drain, strategy)

    def _apply_upsert_node_events(self, node_id, events):
        return self.store.upsert_node_events(node_id, events)

    def _apply_upsert_job(self, job, keep_versions=6):
        return self.store.upsert_job(job, keep_versions)

    def _apply_set_job_stability(self, namespace, job_id, version, stable):
        return self.store.set_job_stability(
            namespace, job_id, version, stable
        )

    def _apply_delete_job(self, namespace, job_id):
        return self.store.delete_job(namespace, job_id)

    def _apply_upsert_evals(self, evals, now=None):
        return self.store.upsert_evals(evals, now)

    def _apply_register_job_federated(self, job, ev, now=None):
        """Cross-region fan-out registration: job + its triggering
        eval as ONE log entry, so the target region can never hold a
        registered job without its eval (or vice versa) across a
        fan-out retry.  The command id is the fan-out's per-region
        id — a re-fanned registration dedups in apply() and returns
        this first apply's eval unchanged.  Timestamps and the eval
        id are proposer-fixed so every replica applies identically."""
        self.store.upsert_job(job, 6)
        if ev is not None:
            ev.job_modify_index = job.modify_index
            self.store.upsert_evals([ev], now)
        return ev

    def _apply_delete_eval(self, eval_id):
        return self.store.delete_eval(eval_id)

    def _apply_upsert_allocs(self, allocs):
        return self.store.upsert_allocs(allocs)

    def _apply_upsert_csi_volume(self, volume):
        return self.store.upsert_csi_volume(volume)

    def _apply_deregister_csi_volume(self, namespace, volume_id, force=False):
        return self.store.deregister_csi_volume(namespace, volume_id, force)

    def _apply_release_csi_claims_for_alloc(self, alloc_id):
        return self.store.release_csi_claims_for_alloc(alloc_id)

    def _apply_upsert_scaling_event(self, namespace, job_id, group, event):
        return self.store.upsert_scaling_event(
            namespace, job_id, group, event
        )

    def _apply_upsert_deployment(self, deployment):
        return self.store.upsert_deployment(deployment)

    def _apply_upsert_namespace(self, ns):
        return self.store.upsert_namespace(ns)

    def _apply_reconcile_job_summaries(self):
        return self.store.reconcile_job_summaries()

    def _apply_delete_namespace(self, name):
        return self.store.delete_namespace(name)

    def _apply_set_scheduler_config(self, config):
        return self.store.set_scheduler_config(config)

    def _apply_set_autopilot_config(self, config):
        return self.store.set_autopilot_config(config)

    def _apply_leadership_barrier(self, gen):
        """A newly established leader's first replicated command: move
        the fence so any still-in-flight command stamped by an OLDER
        generation (a deposed leader's wave) is rejected under the
        apply on every replica (reference: the establishLeadership
        barrier, leader.go:222, hardened into the log itself)."""
        self.leadership_fence = max(self.leadership_fence, gen)
        return self.leadership_fence

    def _apply_upsert_plan_results(self, result, eval_id, leader_gen=None):
        if (
            leader_gen is not None
            and leader_gen < self.leadership_fence
        ):
            # a deposed leader's wave must not commit: the plan was
            # computed against scheduling state that predates the new
            # leader's restore.  Raised (not returned) so the proposer
            # side fails its future and nacks the eval for redelivery.
            raise StaleLeadershipError(leader_gen, self.leadership_fence)
        if getattr(result, "normalized", False):
            result = denormalize_plan_result(self.store, result)
        index = self.store.upsert_plan_results(result, eval_id)
        if eval_id:
            # flight recorder: the replicated-apply path's commit mark
            # (single-process servers commit via the store directly
            # and get only the store.commit event)
            TRACE.event(
                eval_id, "fsm.apply",
                kind="upsert_plan_results", index=index,
            )
        return index

    # ACL commands ------------------------------------------------------

    def _apply_acl_upsert_policy(self, policy):
        self.acls.upsert_policy(policy)

    def _apply_acl_delete_policy(self, name):
        self.acls.delete_policy(name)

    def _apply_acl_create_token(self, token):
        return self.acls.create_token(token)

    def _apply_acl_delete_token(self, accessor_id):
        self.acls.delete_token(accessor_id)

    def _apply_acl_bootstrap(self, token):
        self.acls.tokens_by_accessor[token.accessor_id] = token
        self.acls.tokens_by_secret[token.secret_id] = token
        return token
