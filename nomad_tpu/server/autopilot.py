"""Autopilot: automated server-fleet hygiene (reference
nomad/autopilot.go, which delegates to consul/autopilot: dead-server
cleanup, health tracking, failure-tolerance stats).

The leader periodically reconciles gossip membership against the raft
configuration: servers gossip marks failed/left get removed from the
raft peer set — but only while a quorum of the original configuration
stays intact, so a partition can never talk the leader into shrinking
below safety (reference autopilot.go pruneDeadServers' quorum check).
The reverse direction runs too: a gossip-alive server missing from the
configuration gets re-added (reference leader.go reconcileMember ->
addRaftPeer), so a hard-killed server that restarts at the same
address after cleanup pruned it rejoins replication instead of
sitting alive-but-empty forever.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class AutopilotConfig:
    """(reference structs.go AutopilotConfig; operator API surface)"""

    cleanup_dead_servers: bool = True
    last_contact_threshold_s: float = 0.2
    max_trailing_logs: int = 250
    server_stabilization_time_s: float = 10.0
    enable_redundancy_zones: bool = False
    disable_upgrade_migration: bool = False


@dataclass
class ServerHealth:
    """(reference autopilot ServerHealth)"""

    id: str = ""
    name: str = ""
    address: str = ""
    healthy: bool = True
    voter: bool = True
    last_contact_s: float = 0.0
    last_index: int = 0
    stable_since: float = 0.0


class Autopilot:
    def __init__(
        self,
        cluster,
        config: Optional[AutopilotConfig] = None,
        check_interval: float = 1.0,
    ) -> None:
        self.cluster = cluster
        self._default_config = config or AutopilotConfig()
        self.check_interval = check_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.removed: List[str] = []
        self.readded: List[str] = []

    @property
    def config(self) -> AutopilotConfig:
        """Operator-set config from replicated state when present
        (reference: AutopilotConfig lives in raft, operator_endpoint.go
        AutopilotSetConfiguration), else the compiled-in defaults."""
        store = getattr(self.cluster, "store", None)
        get = getattr(store, "get_autopilot_config", None)
        stored = get() if callable(get) else None
        return stored or self._default_config

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="autopilot", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval):
            try:
                if self.cluster.is_leader():
                    self.prune_dead_servers()
                    self.readd_joined_servers()
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------

    def _members_by_status(self) -> Dict[str, List]:
        out: Dict[str, List] = {"alive": [], "dead": [], "left": []}
        for m in self.cluster.gossip.all_members():
            out.setdefault(m.status, []).append(m)
        return out

    def prune_dead_servers(self) -> List[str]:
        """Remove failed/left servers from the raft configuration when
        quorum is preserved (reference autopilot.go pruneDeadServers).
        Returns the addresses removed this pass."""
        if not self.config.cleanup_dead_servers:
            return []
        raft = self.cluster.raft
        peers = set(raft.peers) | {raft.addr}
        members = self._members_by_status()
        dead = [
            m.addr
            for m in members["dead"] + members["left"]
            if m.addr in peers and m.addr != raft.addr
        ]
        if not dead:
            return []
        # quorum guard: the reference refuses to remove more than
        # (peers-1)/2 — removal must leave a majority of the original
        # configuration alive
        if len(dead) > (len(peers) - 1) // 2:
            return []
        removed = []
        for addr in dead:
            # only report removals that actually committed; a failed
            # config change is retried on the next pass
            if self.cluster.broadcast_peer_removal(addr) is not False:
                removed.append(addr)
        self.removed.extend(removed)
        return removed

    def readd_joined_servers(self) -> List[str]:
        """Re-add gossip-alive same-region servers missing from the
        raft configuration (reference leader.go reconcileMember ->
        addRaftPeer).  Dead-server cleanup pruned a hard-killed
        server; when it restarts at the same address it refutes the
        DEAD rumor and is alive in serf again — but absent from the
        peer set the leader never replicates to it, so it would sit
        READY with an empty store forever.  Gated on the member being
        stably alive (reference ServerStabilizationTime) so a flapping
        server is not re-added mid-flap.  Returns the addresses added
        this pass."""
        raft = self.cluster.raft
        peers = set(raft.peers) | {raft.addr}
        now = time.monotonic()
        window = self.config.server_stabilization_time_s
        region = getattr(self.cluster, "region", None)
        added: List[str] = []
        for m in self.cluster.gossip.alive_members():
            if m.addr in peers:
                continue
            if getattr(m, "role", "server") != "server":
                continue
            # the WAN pool carries other regions' servers for
            # federation routing; they belong to their own raft
            if region is not None and m.region != region:
                continue
            if now - m.status_time < window:
                continue
            if self.cluster.broadcast_peer_add(m.addr) is not False:
                added.append(m.addr)
        self.readded.extend(added)
        return added

    # ------------------------------------------------------------------

    def server_health(self) -> List[ServerHealth]:
        """(reference operator autopilot health endpoint)"""
        raft = self.cluster.raft
        statuses = {
            m.addr: m.status
            for m in self.cluster.gossip.all_members()
        }
        out = []
        for addr in [raft.addr] + list(raft.peers):
            # a configured raft peer that gossip has never seen is not
            # healthy — it has yet to join (the reference requires a
            # serf member + passing health to count a server healthy)
            status = "alive" if addr == raft.addr else statuses.get(
                addr, "failed"
            )
            out.append(
                ServerHealth(
                    id=addr,
                    name=addr,
                    address=addr,
                    healthy=status == "alive",
                    voter=True,
                )
            )
        return out

    def stats(self) -> Dict:
        health = self.server_health()
        healthy = sum(1 for h in health if h.healthy)
        return {
            "Healthy": healthy == len(health),
            "NumServers": len(health),
            "NumHealthy": healthy,
            "FailureTolerance": max(0, (healthy - 1) // 2),
        }
