"""SWIM-style gossip membership (reference: hashicorp/serf + memberlist,
wired in nomad/serf.go and nomad/server.go:174 setupSerf).

Implements the memberlist failure-detector loop the reference gets from
SWIM: periodic random probes, indirect probes through k peers on a
miss, suspicion with refutation by incarnation number, and piggybacked
membership updates on every message.  Servers across regions join one
pool (the reference's WAN serf), giving region federation its routing
table (`members_in_region`) and the agent its `server members` view.

Events (member-join / member-failed / member-leave) surface through a
callback, the way the reference pumps serf events into reconcileCh.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..raft.transport import TransportError

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"
LEFT = "left"

# precedence for equal incarnation numbers (memberlist semantics:
# a claim can only be overridden by a higher incarnation or a
# "stronger" state at the same incarnation)
_PRECEDENCE = {ALIVE: 0, SUSPECT: 1, DEAD: 2, LEFT: 3}


@dataclass
class Member:
    name: str
    addr: str
    region: str = "global"
    role: str = "server"
    incarnation: int = 0
    status: str = ALIVE
    status_time: float = field(default_factory=time.monotonic)
    # HTTP advertise address (host:port), rumored alongside the RPC
    # addr so other REGIONS learn where to redirect HTTP traffic —
    # the X-Nomad-Retry-Region shed hint is built from these.  Empty
    # until the member's HTTP listener binds and advertises.
    http_addr: str = ""

    def record(self) -> Tuple:
        return (
            self.name,
            self.addr,
            self.region,
            self.role,
            self.incarnation,
            self.status,
            self.http_addr,
        )


class Gossip:
    """One gossip participant.  Does not own a transport slot — the
    owner routes `gossip_*` RPC methods to handle() (the reference
    multiplexes serf onto the same listener as everything else)."""

    def __init__(
        self,
        name: str,
        addr: str,
        transport,
        region: str = "global",
        role: str = "server",
        probe_interval: float = 0.15,
        suspicion_timeout: float = 0.8,
        indirect_probes: int = 2,
        on_event: Optional[Callable[[str, Member], None]] = None,
    ) -> None:
        self.name = name
        self.addr = addr
        self.transport = transport
        self.region = region
        self.probe_interval = probe_interval
        self.suspicion_timeout = suspicion_timeout
        self.indirect_probes = indirect_probes
        self.on_event = on_event

        self._lock = threading.RLock()
        self._leaving = False
        self.members: Dict[str, Member] = {
            name: Member(name, addr, region, role)
        }
        self._probe_ring: List[str] = []
        self._round = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"gossip@{self.name}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def leave(self) -> None:
        """Graceful departure (serf Leave): broadcast LEFT so peers
        don't mark us failed."""
        with self._lock:
            self._leaving = True
            me = self.members[self.name]
            me.incarnation += 1
            me.status = LEFT
            records = [me.record()]
        for peer in self._alive_peers():
            try:
                self.transport.rpc(
                    self.addr, peer.addr, "gossip_ping",
                    {"from": self.name, "updates": records},
                )
            except TransportError:
                pass
        self.stop()

    def advertise_http(self, http_addr: str) -> None:
        """Set our HTTP advertise address and outbid every cached view
        of us with an incarnation bump — without the bump the new
        field would lose the rumor race to any equal-incarnation
        record already circulating.  Piggybacking spreads it from
        here; no broadcast needed."""
        with self._lock:
            me = self.members[self.name]
            me.http_addr = http_addr
            me.incarnation += 1

    # -- joining --------------------------------------------------------

    def join(self, seed_addr: str) -> int:
        """Join a pool via any existing member (serf Join).  Returns
        the number of members learned."""
        resp = self.transport.rpc(
            self.addr,
            seed_addr,
            "gossip_join",
            {"records": self._records()},
        )
        before = len(self.members)
        self._merge(resp["records"])
        return len(self.members) - before

    # -- views ----------------------------------------------------------

    def _records(self) -> List[Tuple]:
        with self._lock:
            return [m.record() for m in self.members.values()]

    def force_leave(self, name: str) -> None:
        """Operator eviction of a failed member (serf ForceLeave —
        reference `server force-leave`): mark LEFT locally and gossip
        it so peers stop probing the corpse."""
        with self._lock:
            member = self.members.get(name)
            if member is None:
                return
            member.incarnation += 1
            member.status = LEFT
            records = [member.record()]
        # the originating node fires the same member-leave event its
        # peers will fire from _merge
        self._emit("member-leave", member)
        for peer in self._alive_peers():
            try:
                self.transport.rpc(
                    self.addr, peer.addr, "gossip_ping",
                    {"from": self.name, "updates": records},
                )
            except TransportError:
                pass

    def alive_members(self) -> List[Member]:
        with self._lock:
            return [
                m for m in self.members.values() if m.status == ALIVE
            ]

    def members_in_region(self, region: str) -> List[Member]:
        return [
            m for m in self.alive_members() if m.region == region
        ]

    def all_members(self) -> List[Member]:
        """Every known member regardless of status (autopilot input)."""
        with self._lock:
            return list(self.members.values())

    def member_list(self) -> List[Dict]:
        with self._lock:
            return [
                {
                    "Name": m.name,
                    "Addr": m.addr,
                    "HTTPAddr": m.http_addr,
                    "Region": m.region,
                    "Role": m.role,
                    "Status": m.status,
                    "Incarnation": m.incarnation,
                }
                for m in sorted(
                    self.members.values(), key=lambda m: m.name
                )
            ]

    def _alive_peers(self) -> List[Member]:
        with self._lock:
            return [
                m
                for m in self.members.values()
                if m.name != self.name and m.status in (ALIVE, SUSPECT)
            ]

    # -- probe loop (SWIM failure detector) -----------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._probe_once()
            self._expire_suspects()
            self._round += 1
            # reconnect sweep (memberlist's dead-node push/pull): every
            # few rounds, ping one DEAD member so a healed partition
            # can't leave the pool permanently split — a symmetric
            # partition makes BOTH sides mark the other dead, and
            # without this nobody would ever talk across again
            if self._round % 3 == 0:
                self._reconnect_probe()
            self._stop.wait(self.probe_interval)

    def _reconnect_probe(self) -> None:
        with self._lock:
            dead = [
                m for m in self.members.values() if m.status == DEAD
            ]
        if not dead:
            return
        target = random.choice(dead)
        # a live target sees the DEAD rumor in our piggyback, refutes
        # with a higher incarnation in its reply, and _merge revives it
        self._ping(target.addr)

    def _next_probe_target(self) -> Optional[Member]:
        with self._lock:
            candidates = [m.name for m in self._alive_peers()]
            if not candidates:
                return None
            # randomized round-robin ring (SWIM's probe ordering)
            self._probe_ring = [
                n for n in self._probe_ring if n in candidates
            ]
            if not self._probe_ring:
                self._probe_ring = candidates
                random.shuffle(self._probe_ring)
            name = self._probe_ring.pop()
            return self.members.get(name)

    def _probe_once(self) -> None:
        target = self._next_probe_target()
        if target is None:
            return
        if self._ping(target.addr):
            self._mark(target.name, ALIVE, target.incarnation)
            return
        # indirect probes through k random other peers (SWIM ping-req)
        others = [
            m for m in self._alive_peers() if m.name != target.name
        ]
        random.shuffle(others)
        for relay in others[: self.indirect_probes]:
            try:
                resp = self.transport.rpc(
                    self.addr,
                    relay.addr,
                    "gossip_ping_req",
                    {
                        "from": self.name,
                        "target": target.addr,
                        "updates": self._gossip_payload(),
                    },
                )
                if resp.get("ack"):
                    self._merge(resp.get("updates", ()))
                    self._mark(target.name, ALIVE, target.incarnation)
                    return
            except TransportError:
                continue
        self._suspect(target.name)

    def _ping(self, addr: str) -> bool:
        try:
            resp = self.transport.rpc(
                self.addr,
                addr,
                "gossip_ping",
                {"from": self.name, "updates": self._gossip_payload()},
            )
            self._merge(resp.get("updates", ()))
            return bool(resp.get("ack"))
        except TransportError:
            return False

    def _gossip_payload(self) -> List[Tuple]:
        # full-state piggyback: pools are O(servers), not O(nodes), so
        # shipping the whole view every ping is cheap and converges fast
        return self._records()

    # -- state merging ---------------------------------------------------

    def _mark(self, name: str, status: str, incarnation: int) -> None:
        """Direct observation (an ack from the member itself) clears a
        local suspicion at the same incarnation."""
        with self._lock:
            m = self.members.get(name)
            if m is None:
                return
            if (
                status == ALIVE
                and m.status == SUSPECT
                and incarnation >= m.incarnation
            ):
                m.status = ALIVE
                m.status_time = time.monotonic()

    def _suspect(self, name: str) -> None:
        with self._lock:
            m = self.members.get(name)
            if m is None or m.status != ALIVE:
                return
            m.status = SUSPECT
            m.status_time = time.monotonic()

    def _expire_suspects(self) -> None:
        events = []
        with self._lock:
            now = time.monotonic()
            for m in self.members.values():
                if (
                    m.status == SUSPECT
                    and now - m.status_time > self.suspicion_timeout
                ):
                    m.status = DEAD
                    m.status_time = now
                    events.append(("member-failed", m))
        for kind, m in events:
            self._emit(kind, m)

    def _emit(self, kind: str, member: Member) -> None:
        if self.on_event is not None:
            try:
                self.on_event(kind, member)
            except Exception:  # noqa: BLE001 — observer fault
                pass

    def _merge(self, records) -> None:
        events = []
        with self._lock:
            for rec in records:
                # records from a pre-http_addr peer are 6-tuples;
                # tolerate both wire shapes so a mixed-version pool
                # still converges (memberlist's protocol-version skew)
                name, addr, region, role, inc, status = rec[:6]
                http = rec[6] if len(rec) > 6 else ""
                if name == self.name:
                    # refutation (SWIM): if the pool thinks we're gone,
                    # outbid the rumor with a higher incarnation.  A
                    # stale LEFT from a previous process lifetime is
                    # refuted too (rejoin after graceful leave), but not
                    # while we're actually leaving.
                    me = self.members[self.name]
                    refutable = (SUSPECT, DEAD) if self._leaving else (
                        SUSPECT,
                        DEAD,
                        LEFT,
                    )
                    if status in refutable and inc >= me.incarnation:
                        me.incarnation = inc + 1
                        me.status = ALIVE
                    continue
                cur = self.members.get(name)
                if cur is None:
                    m = Member(
                        name, addr, region, role, inc, status,
                        http_addr=http,
                    )
                    self.members[name] = m
                    if status == ALIVE:
                        events.append(("member-join", m))
                    continue
                if inc > cur.incarnation or (
                    inc == cur.incarnation
                    and _PRECEDENCE[status] > _PRECEDENCE[cur.status]
                ):
                    old_status = cur.status
                    cur.incarnation = inc
                    cur.status = status
                    cur.status_time = time.monotonic()
                    cur.addr, cur.region, cur.role = addr, region, role
                    if http:
                        cur.http_addr = http
                    if status == ALIVE and old_status != ALIVE:
                        events.append(("member-join", cur))
                    elif status == DEAD and old_status != DEAD:
                        events.append(("member-failed", cur))
                    elif status == LEFT and old_status != LEFT:
                        events.append(("member-leave", cur))
        for kind, m in events:
            self._emit(kind, m)

    # -- inbound handlers ------------------------------------------------

    def handle(self, method: str, payload: dict) -> dict:
        if method == "gossip_ping":
            self._merge(payload.get("updates", ()))
            return {"ack": True, "updates": self._gossip_payload()}
        if method == "gossip_ping_req":
            # probe the target on behalf of the requester; the
            # requester piggybacks rumors exactly like a direct ping
            self._merge(payload.get("updates", ()))
            ok = self._ping(payload["target"])
            return {"ack": ok, "updates": self._gossip_payload()}
        if method == "gossip_join":
            self._merge(payload.get("records", ()))
            return {"records": self._records()}
        raise ValueError(f"unknown gossip rpc {method!r}")
