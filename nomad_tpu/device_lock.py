"""Cross-process accelerator lock.

A tunneled single-chip TPU session is process-exclusive: two jax
processes initializing the backend concurrently wedge the tunnel for
everyone (including future processes — the stale session can outlive
both).  Round 3 lost its whole benchmark to exactly that.

``ensure_device_lock()`` takes an exclusive ``flock`` on a well-known
lockfile *before* jax backend init and holds it for the life of the
process, so a second launch **blocks** (with a log line saying whose
pid holds the chip) instead of corrupting the session.

The lock is only taken when a real accelerator may be in play:
``JAX_PLATFORMS=cpu`` (the test suite's virtual-mesh mode) skips it —
CPU backends are not exclusive and tests may run in parallel.

Env knobs:
  NOMAD_TPU_DEVICE_LOCK       lockfile path (default
                              /tmp/nomad_tpu_device.lock)
  NOMAD_TPU_DEVICE_LOCK_WAIT  seconds to wait before giving up
                              (default: block forever); 0 disables
                              the lock entirely (expert override)
"""
from __future__ import annotations

import errno
import logging
import os
import threading
import time

LOG = logging.getLogger("nomad_tpu.device_lock")

_LOCK_PATH_ENV = "NOMAD_TPU_DEVICE_LOCK"
_LOCK_WAIT_ENV = "NOMAD_TPU_DEVICE_LOCK_WAIT"
_DEFAULT_PATH = "/tmp/nomad_tpu_device.lock"

_state_lock = threading.Lock()
_held_fd: int | None = None


def _cpu_only(plats: str) -> bool:
    """Whether a JAX_PLATFORMS value names ONLY the cpu backend —
    the single parse shared by the lock gate and the config
    alignment so the two can never disagree."""
    return set(p.strip() for p in plats.split(",")) <= {"cpu"}


def _needs_lock() -> bool:
    """Lock only when JAX_PLATFORMS explicitly names a non-CPU
    backend (tunneled single-chip deployments always set it, e.g.
    ``axon``).  Unset or cpu-only means no exclusive session is in
    play: a server agent and a client agent sharing a CPU-only box
    must not serialize on (or deadlock over) a process-lifetime
    lock.  Bare-metal TPU without the var fails fast via libtpu's
    own process-exclusivity check rather than wedging a tunnel."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if not plats:
        return False
    return not _cpu_only(plats)


def align_jax_platforms() -> None:
    """Make jax's CONFIG agree with an explicit ``JAX_PLATFORMS=cpu``.

    A tunnel-plugin sitecustomize may pin ``jax_platforms`` via
    ``jax.config`` at interpreter start, and config beats the env var
    — so a process the operator explicitly marked CPU-only still
    dials the tunneled accelerator the first time anything compiles
    (background warm threads included), hanging on a wedged session
    and adding contention that keeps it wedged.  Call before any jax
    work in processes that honor the env contract."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if not plats:
        return
    if not _cpu_only(plats):
        return  # only force the CPU-only case; never narrow axon
    try:
        import jax

        if str(getattr(jax.config, "jax_platforms", "") or "") != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — alignment is best-effort
        pass


def scrub_accelerator_env(
    base: dict | None = None,
) -> dict:
    """Environment for task-runtime subprocesses (executors, sidecar
    proxies, logmon): force the CPU backend and drop the tunnel-plugin
    activation vars, so a helper process can never claim the exclusive
    single-chip session.  Round 3's tunnel wedge traces to exactly
    this — a leftover test executor held the chip for hours because
    the site-wide plugin registration runs in every python process."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    for var in (
        "PALLAS_AXON_POOL_IPS",
        "AXON_POOL_SVC_OVERRIDE",
        "PALLAS_AXON_REMOTE_COMPILE",
    ):
        env.pop(var, None)
    return env


def ensure_device_lock(
    what: str = "jax backend", wait_s: float | None = None
) -> bool:
    """Acquire (once per process) the exclusive accelerator lock.

    ``wait_s``: seconds to wait before giving up (callers with their
    own deadline, e.g. the client fingerprint, pass theirs); None
    defers to NOMAD_TPU_DEVICE_LOCK_WAIT, default block-forever.

    Returns True when the lock is held (or intentionally skipped for a
    CPU-only backend / expert opt-out), False when a bounded wait
    expired.  Idempotent and thread-safe; the fd is held until process
    exit so the OS releases it even on a crash."""
    global _held_fd
    if not _needs_lock():
        # CPU-only by explicit env: also make jax's config agree so
        # no background thread dials the tunnel anyway
        align_jax_platforms()
        return True
    wait_env = os.environ.get(_LOCK_WAIT_ENV)
    if wait_env is not None:
        try:
            env_wait = float(wait_env)
        except ValueError:
            env_wait = -1.0
        if env_wait == 0:
            return True  # explicit opt-out
        if wait_s is None:
            wait_s = env_wait
    if wait_s is None:
        wait_s = -1.0  # block forever
    with _state_lock:
        if _held_fd is not None:
            return True
        import fcntl

        path = os.environ.get(_LOCK_PATH_ENV, _DEFAULT_PATH)
        try:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o666)
            try:
                # the 0o666 mode is masked by umask on create: open
                # it up so a different-uid process can take the lock
                # later instead of crashing on PermissionError
                os.fchmod(fd, 0o666)
            except OSError:
                pass
        except OSError as exc:
            # a lockfile we cannot open (foreign owner + restrictive
            # mode) must degrade to a loud warning, not a crash in
            # the middle of scheduler construction
            LOG.warning(
                "accelerator lockfile %s unusable (%s); proceeding "
                "WITHOUT cross-process exclusion",
                path,
                exc,
            )
            return True
        deadline = (
            time.monotonic() + wait_s if wait_s > 0 else None
        )
        logged = False
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as exc:
                if exc.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
                if not logged:
                    holder = ""
                    try:
                        holder = os.read(fd, 64).decode(
                            "ascii", "replace"
                        ).strip()
                        os.lseek(fd, 0, os.SEEK_SET)
                    except OSError:
                        pass
                    LOG.warning(
                        "accelerator lock %s held%s; waiting for %s "
                        "(a second jax process would wedge the "
                        "single-chip tunnel)",
                        path,
                        f" by {holder}" if holder else "",
                        what,
                    )
                    logged = True
                if deadline is not None and time.monotonic() > deadline:
                    os.close(fd)
                    return False
                time.sleep(0.5)
        try:
            os.ftruncate(fd, 0)
            os.write(
                fd, f"pid={os.getpid()} what={what}\n".encode()
            )
        except OSError:
            pass
        _held_fd = fd
        if logged:
            LOG.warning("accelerator lock acquired after waiting")
        return True


def release_device_lock() -> None:
    """Release early (normally unnecessary — process exit releases)."""
    global _held_fd
    with _state_lock:
        if _held_fd is None:
            return
        import fcntl

        try:
            fcntl.flock(_held_fd, fcntl.LOCK_UN)
            os.close(_held_fd)
        except OSError:
            pass
        _held_fd = None
