"""Agent configuration (reference command/agent/config.go +
config_parse.go): HCL or JSON config files merged with defaults and
flags.

    # agent.hcl
    data_dir = "/var/lib/nomad-tpu"
    server {
      enabled        = true
      num_schedulers = 4
      batch_pipeline = true
      heartbeat_ttl  = "30s"
    }
    client {
      enabled = true
      drivers = ["exec", "raw_exec", "mock_driver"]
    }
    http { port = 4646 }
    acl { enabled = false }
    telemetry { prometheus = true }
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class ServerConfig:
    enabled: bool = True
    num_schedulers: int = 2
    batch_pipeline: bool = True
    heartbeat_ttl_s: float = 30.0
    seed: Optional[int] = None


@dataclass
class ClientConfig:
    enabled: bool = False
    drivers: List[str] = field(
        default_factory=lambda: ["exec", "raw_exec", "mock_driver"]
    )
    include_tpu_fingerprint: bool = True
    heartbeat_interval_s: float = 10.0


@dataclass
class DeviceConfig:
    """Accelerator supervisor knobs (nomad_tpu/device).  ``None``
    defers to the NOMAD_TPU_* env knob (and its default), so a config
    file only pins what it names:

        device {
          probe_interval  = "30s"
          probe_timeout   = "10s"
          watchdog_factor = 20
          watchdog_min    = "5s"
          watchdog_max    = "2m"
        }
    """

    probe_interval_s: Optional[float] = None
    probe_timeout_s: Optional[float] = None
    watchdog_factor: Optional[float] = None
    watchdog_min_s: Optional[float] = None
    watchdog_max_s: Optional[float] = None
    lost_probes: Optional[int] = None
    recover_canaries: Optional[int] = None
    init_grace_s: Optional[float] = None


@dataclass
class HTTPConfig:
    host: str = "127.0.0.1"
    port: int = 4646


@dataclass
class ACLConfig:
    enabled: bool = False


@dataclass
class ConsulConfig:
    """(reference nomad/structs/config/consul.go)"""

    address: str = ""  # empty = in-framework catalog only
    token: str = ""


@dataclass
class VaultConfig:
    """(reference nomad/structs/config/vault.go)"""

    address: str = ""  # empty = local secrets providers only
    token: str = ""


@dataclass
class AgentConfig:
    data_dir: str = ""
    name: str = ""
    datacenter: str = "dc1"
    region: str = "global"
    server: ServerConfig = field(default_factory=ServerConfig)
    client: ClientConfig = field(default_factory=ClientConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)
    http: HTTPConfig = field(default_factory=HTTPConfig)
    acl: ACLConfig = field(default_factory=ACLConfig)
    consul: ConsulConfig = field(default_factory=ConsulConfig)
    vault: VaultConfig = field(default_factory=VaultConfig)
    bridge_port: Optional[int] = None


def _duration_s(value, default: float) -> float:
    """Canonical Go-style duration parser ("1h30m", "10s", "100ms",
    bare numbers).  The single shared implementation — jobspec and the
    mock driver import this one; keeping copies in sync is how the
    '100ms parses as 100 minutes' alternation bug happened."""
    if value is None:
        return default
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    try:
        return float(s)
    except ValueError:
        pass
    total = 0.0
    # 'ms' must precede 'm' in the alternation or "100ms" reads as
    # 100 minutes
    for num, unit in re.findall(r"(-?[\d.]+)(ms|h|m|s)", s):
        total += float(num) * {"h": 3600, "m": 60, "s": 1, "ms": 0.001}[
            unit
        ]
    return total if total else default


def _first(value, default=None):
    if isinstance(value, list):
        return value[0] if value else default
    return value if value is not None else default


def config_from_dict(raw: Dict) -> AgentConfig:
    cfg = AgentConfig()
    cfg.data_dir = raw.get("data_dir", "")
    cfg.name = raw.get("name", "")
    cfg.datacenter = raw.get("datacenter", "dc1")
    cfg.region = raw.get("region", "global")

    server = _first(raw.get("server"), {}) or {}
    cfg.server = ServerConfig(
        enabled=bool(server.get("enabled", True)),
        num_schedulers=int(server.get("num_schedulers", 2)),
        batch_pipeline=bool(server.get("batch_pipeline", True)),
        heartbeat_ttl_s=_duration_s(server.get("heartbeat_ttl"), 30.0),
        seed=server.get("seed"),
    )
    client = _first(raw.get("client"), {}) or {}
    cfg.client = ClientConfig(
        enabled=bool(client.get("enabled", False)),
        drivers=client.get("drivers")
        or ["exec", "raw_exec", "mock_driver"],
        include_tpu_fingerprint=bool(
            client.get("include_tpu_fingerprint", True)
        ),
        heartbeat_interval_s=_duration_s(
            client.get("heartbeat_interval"), 10.0
        ),
    )
    device = _first(raw.get("device"), {}) or {}

    def _dur_or_none(key):
        value = device.get(key)
        return None if value is None else _duration_s(value, 0.0)

    cfg.device = DeviceConfig(
        probe_interval_s=_dur_or_none("probe_interval"),
        probe_timeout_s=_dur_or_none("probe_timeout"),
        watchdog_factor=(
            None
            if device.get("watchdog_factor") is None
            else float(device["watchdog_factor"])
        ),
        watchdog_min_s=_dur_or_none("watchdog_min"),
        watchdog_max_s=_dur_or_none("watchdog_max"),
        lost_probes=(
            None
            if device.get("lost_probes") is None
            else int(device["lost_probes"])
        ),
        recover_canaries=(
            None
            if device.get("recover_canaries") is None
            else int(device["recover_canaries"])
        ),
        init_grace_s=_dur_or_none("init_grace"),
    )
    http = _first(raw.get("http"), {}) or {}
    cfg.http = HTTPConfig(
        host=http.get("host", "127.0.0.1"),
        port=int(http.get("port", 4646)),
    )
    acl = _first(raw.get("acl"), {}) or {}
    cfg.acl = ACLConfig(enabled=bool(acl.get("enabled", False)))
    consul = _first(raw.get("consul"), {}) or {}
    cfg.consul = ConsulConfig(
        address=consul.get("address", ""),
        token=consul.get("token", ""),
    )
    vault = _first(raw.get("vault"), {}) or {}
    cfg.vault = VaultConfig(
        address=vault.get("address", ""),
        token=vault.get("token", ""),
    )
    if raw.get("bridge_port") is not None:
        cfg.bridge_port = int(raw["bridge_port"])
    return cfg


def load_config(path: str) -> AgentConfig:
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return config_from_dict(json.loads(text))
    # reuse the jobspec HCL machinery for the config dialect
    from .jobspec import _Parser, _tokenize

    tree = _Parser(_tokenize(text)).parse_body(stop=None)
    return config_from_dict(tree)
