"""Minimal built-in web UI (the parity nod to the reference's Ember app
under ui/ — same data, one self-contained page against the /v1 API).
Served at /ui by the HTTP server."""

UI_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<style>
  :root { color-scheme: light dark; }
  body { font-family: system-ui, sans-serif; margin: 2rem;
         max-width: 72rem; }
  h1 { font-size: 1.3rem; }
  h2 { font-size: 1.05rem; margin-top: 1.6rem; }
  table { border-collapse: collapse; width: 100%; font-size: .85rem; }
  th, td { text-align: left; padding: .3rem .6rem;
           border-bottom: 1px solid #8884; }
  code { font-size: .8rem; }
  .ok  { color: #2a9d2a; }
  .bad { color: #d43a3a; }
  #err { color: #d43a3a; }
</style>
</head>
<body>
<h1>nomad-tpu <small id="leader"></small></h1>
<div id="err"></div>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Allocations</h2><table id="allocs"></table>
<script>
async function j(p) {
  const r = await fetch(p);
  if (!r.ok) throw new Error(p + ": " + r.status);
  return r.json();
}
function esc(v) {
  return String(v ?? "").replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;",
  })[c]);
}
function row(cells, tag) {
  return "<tr>" + cells.map(c => `<${tag||"td"}>${c}</${tag||"td"}>`)
    .join("") + "</tr>";
}
function code(v) { return `<code>${esc(v).slice(0, 8)}</code>`; }
function badge(s, good) {
  return `<span class="${good.includes(s) ? "ok" : "bad"}">` +
    esc(s) + "</span>";
}
async function refresh() {
  try {
    const [jobs, nodes, allocs, leader] = await Promise.all([
      j("/v1/jobs"), j("/v1/nodes"), j("/v1/allocations"),
      j("/v1/status/leader"),
    ]);
    document.getElementById("leader").textContent =
      "leader: " + JSON.stringify(leader);
    document.getElementById("jobs").innerHTML =
      row(["ID","Type","Priority","Status"], "th") +
      jobs.map(x => row([esc(x.ID), esc(x.Type), esc(x.Priority),
        badge(x.Status, ["running","complete"])])).join("");
    document.getElementById("nodes").innerHTML =
      row(["ID","Name","DC","Status","Eligibility"], "th") +
      nodes.map(x => row([
        code(x.ID), esc(x.Name),
        esc(x.Datacenter), badge(x.Status, ["ready"]),
        esc(x.SchedulingEligibility)])).join("");
    document.getElementById("allocs").innerHTML =
      row(["ID","Job","Group","Node","Desired","Client"], "th") +
      allocs.map(x => row([
        code(x.id), esc(x.job_id),
        esc(x.task_group), code(x.node_id),
        esc(x.desired_status),
        badge(x.client_status, ["running","complete"])])).join("");
    document.getElementById("err").textContent = "";
  } catch (e) {
    document.getElementById("err").textContent = String(e);
  }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
