"""Built-in web UI (the parity nod to the reference's Ember app under
ui/ — same data, one self-contained page against the /v1 API).

Live updates ride the API's blocking queries: each list view long-polls
its endpoint with ?index=N&wait=30 (reference rpc.go:780 blockingRPC;
the Ember UI's live updates poll the same way) and re-renders only when
the X-Nomad-Index advances.  Hash routes provide drill-down detail:
#/jobs, #/job/<id>, #/nodes, #/node/<id>, #/allocs, #/alloc/<id>.
Served at /ui by the HTTP server.
"""

UI_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<style>
  :root { color-scheme: light dark; }
  body { font-family: system-ui, sans-serif; margin: 2rem;
         max-width: 76rem; }
  h1 { font-size: 1.3rem; }
  h2 { font-size: 1.05rem; margin-top: 1.6rem; }
  nav a { margin-right: 1rem; }
  table { border-collapse: collapse; width: 100%; font-size: .85rem; }
  th, td { text-align: left; padding: .3rem .6rem;
           border-bottom: 1px solid #8884; }
  code, pre { font-size: .8rem; }
  pre { background: #8881; padding: .6rem; overflow-x: auto; }
  .ok  { color: #2a9d2a; }
  .bad { color: #d43a3a; }
  #err { color: #d43a3a; }
  #live { font-size: .75rem; opacity: .6; }
  .kv { display: grid; grid-template-columns: repeat(4, 1fr);
        gap: .4rem 1rem; font-size: .85rem; margin: .6rem 0; }
  .kv .k { opacity: .6; margin-right: .4rem; }
  .tgsum { margin: .45rem 0; font-size: .85rem; }
  .bar { display: flex; height: .6rem; border-radius: .3rem;
         overflow: hidden; background: #8882; margin: .15rem 0;
         max-width: 32rem; }
  .seg.running  { background: #2a9d2a; }
  .seg.starting { background: #7ec97e; }
  .seg.queued   { background: #c9a227; }
  .seg.complete { background: #4a7dbd; }
  .seg.failed   { background: #d43a3a; }
  .seg.lost     { background: #8a4ad4; }
</style>
</head>
<body>
<h1>nomad-tpu <small id="leader"></small> <span id="live"></span></h1>
<nav>
  <a href="#/jobs">Jobs</a><a href="#/nodes">Nodes</a
  ><a href="#/allocs">Allocations</a>
</nav>
<div id="err"></div>
<div id="view"></div>
<script>
function esc(v) {
  return String(v ?? "").replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;",
  })[c]);
}
function row(cells, tag) {
  return "<tr>" + cells.map(c => `<${tag||"td"}>${c}</${tag||"td"}>`)
    .join("") + "</tr>";
}
function link(href, text) {
  // href is attacker-influenced (job ids): escape for the attribute
  return `<a href="#${esc(href)}">${text}</a>`;
}
function code(v) { return `<code>${esc(v).slice(0, 8)}</code>`; }
function fmtTime(t, timeOnly) {
  const s = new Date(1000 * (t || 0)).toISOString()
    .replace("T", " ");
  return esc(timeOnly ? s.slice(11, 19) : s.slice(0, 19));
}
function badge(s, good) {
  return `<span class="${good.includes(s) ? "ok" : "bad"}">` +
    esc(s) + "</span>";
}
async function j(p) {
  const r = await fetch(p);
  if (!r.ok) throw new Error(p + ": " + r.status);
  return r.json();
}

// ---- blocking-query live poller -----------------------------------
// one generation per route; switching routes abandons the old loop
let generation = 0;
async function livePoll(path, render) {
  const gen = generation;
  let index = 0;
  while (gen === generation) {
    try {
      const url = index
        ? `${path}${path.includes("?") ? "&" : "?"}index=${index}&wait=30`
        : path;
      const r = await fetch(url);
      if (!r.ok) throw new Error(path + ": " + r.status);
      const next = parseInt(r.headers.get("X-Nomad-Index") || "0");
      const data = await r.json();
      if (gen !== generation) return;
      render(data);
      document.getElementById("err").textContent = "";
      document.getElementById("live").textContent =
        "live (index " + next + ")";
      index = next || index;
      if (!next) await new Promise(res => setTimeout(res, 2000));
    } catch (e) {
      if (gen !== generation) return;
      document.getElementById("err").textContent = String(e);
      await new Promise(res => setTimeout(res, 2000));
    }
  }
}
function view(html) { document.getElementById("view").innerHTML = html; }

// ---- views ---------------------------------------------------------
function jobsView() {
  view('<h2>Jobs</h2><table id="t"></table>');
  livePoll("/v1/jobs", jobs => {
    document.getElementById("t").innerHTML =
      row(["ID","Type","Priority","Status"], "th") +
      jobs.map(x => row([link("/job/" + x.ID, esc(x.ID)), esc(x.Type),
        esc(x.Priority),
        badge(x.Status, ["running","complete"])])).join("");
  });
}
function nodesView() {
  view('<h2>Nodes</h2><table id="t"></table>');
  livePoll("/v1/nodes", nodes => {
    document.getElementById("t").innerHTML =
      row(["ID","Name","DC","Status","Eligibility"], "th") +
      nodes.map(x => row([
        link("/node/" + x.ID, code(x.ID)), esc(x.Name),
        esc(x.Datacenter), badge(x.Status, ["ready"]),
        esc(x.SchedulingEligibility)])).join("");
  });
}
function allocRows(allocs) {
  return row(["ID","Job","Group","Node","Desired","Client"], "th") +
    allocs.map(x => row([
      link("/alloc/" + x.id, code(x.id)),
      link("/job/" + x.job_id, esc(x.job_id)),
      esc(x.task_group),
      link("/node/" + x.node_id, code(x.node_id)),
      esc(x.desired_status),
      badge(x.client_status, ["running","complete"])])).join("");
}
function allocsView() {
  view('<h2>Allocations</h2><table id="t"></table>');
  livePoll("/v1/allocations", allocs => {
    document.getElementById("t").innerHTML = allocRows(allocs);
  });
}
// ---- job detail (the information of the reference's
// ui/app/routes/jobs/job: header facts, per-group summary bar,
// task-group resources, live allocs, deployment health, evals) ------
function kvGrid(pairs) {
  return '<div class="kv">' + pairs.map(([k, v]) =>
    `<div><span class="k">${esc(k)}</span> ${v}</div>`).join("") +
    "</div>";
}
function summaryBar(name, s) {
  const states = [
    ["Running", "running"], ["Starting", "starting"],
    ["Queued", "queued"], ["Complete", "complete"],
    ["Failed", "failed"], ["Lost", "lost"],
  ];
  const total = states.reduce((n, [k]) => n + (s[k] || 0), 0) || 1;
  const segs = states.map(([k, cls]) => (s[k] || 0) ?
    `<span class="seg ${cls}" style="width:${100 * s[k] / total}%"
       title="${k}: ${s[k]}"></span>` : "").join("");
  const counts = states.filter(([k]) => s[k])
    .map(([k]) => `${k.toLowerCase()} ${s[k]}`).join(" · ");
  return `<div class="tgsum"><b>${esc(name)}</b>
    <div class="bar">${segs}</div>
    <small>${esc(counts) || "no allocations"}</small></div>`;
}
function jobView(id) {
  view(`<h2 id="jh">Job ${esc(id)}</h2><div id="facts"></div>
    <h2>Task group summary</h2><div id="sum"></div>
    <h2>Task groups</h2><table id="tg"></table>
    <h2>Allocations</h2><table id="a"></table>
    <h2>Deployments</h2><table id="dep"></table>
    <h2>Evaluations</h2><table id="e"></table>`);
  j(`/v1/job/${id}`).then(job => {
    document.getElementById("jh").textContent =
      `Job ${job.name || job.id}`;
    document.getElementById("facts").innerHTML = kvGrid([
      ["ID", `<code>${esc(job.id)}</code>`],
      ["Status", badge(job.status, ["running", "complete"])],
      ["Type", esc(job.type)],
      ["Priority", esc(job.priority)],
      ["Version", esc(job.version)],
      ["Namespace", esc(job.namespace)],
      ["Datacenters", esc((job.datacenters || []).join(", "))],
      ["Stopped", esc(job.stop ? "yes" : "no")],
    ]);
    document.getElementById("tg").innerHTML =
      row(["Group", "Count", "Tasks", "CPU (MHz)", "Memory (MiB)",
           "Disk (MiB)"], "th") +
      (job.task_groups || []).map(g => {
        const cpu = (g.tasks || []).reduce(
          (n, t) => n + ((t.resources || {}).cpu || 0), 0);
        const mem = (g.tasks || []).reduce(
          (n, t) => n + ((t.resources || {}).memory_mb || 0), 0);
        const tasks = (g.tasks || [])
          .map(t => `${esc(t.name)} (${esc(t.driver)})`).join(", ");
        return row([esc(g.name), esc(g.count), tasks, esc(cpu),
          esc(mem), esc((g.ephemeral_disk || {}).size_mb || 300)]);
      }).join("");
  }).catch(e => {
    // render into the section itself: #err is cleared by any
    // concurrently succeeding livePoll, which would hide this
    document.getElementById("facts").innerHTML =
      `<span class="bad">${esc(String(e))}</span>`;
  });
  // the summary + alloc tables ride blocking queries and stay live
  livePoll(`/v1/job/${id}/summary`, s => {
    const groups = s.Summary || s.summary || {};
    document.getElementById("sum").innerHTML =
      Object.entries(groups).map(([g, c]) => summaryBar(g, c)).join("")
      || "<small>no task groups</small>";
  });
  livePoll(`/v1/job/${id}/allocations`, allocs => {
    document.getElementById("a").innerHTML = allocRows(allocs);
  });
  livePoll(`/v1/job/${id}/deployments`, ds => {
    document.getElementById("dep").innerHTML =
      row(["ID", "Version", "Status", "Group", "Desired", "Placed",
           "Healthy", "Unhealthy", "Canaries"], "th") +
      ds.flatMap(d => {
        const groups = Object.entries(d.task_groups || {});
        if (!groups.length) {
          return [row([code(d.id), esc(d.job_version),
            badge(d.status, ["successful", "running"]),
            "", "", "", "", "", ""])];
        }
        return groups.map(([g, st]) => row([
          code(d.id), esc(d.job_version),
          badge(d.status, ["successful", "running"]), esc(g),
          esc(st.desired_total), esc(st.placed_allocs),
          esc(st.healthy_allocs), esc(st.unhealthy_allocs),
          `${(st.placed_canaries || []).length}/${st.desired_canaries}`
          + (st.promoted ? " promoted" : ""),
        ]));
      }).join("");
  });
  j(`/v1/job/${id}/evaluations`).then(evs => {
    document.getElementById("e").innerHTML =
      row(["ID", "TriggeredBy", "Status"], "th") +
      evs.map(x => row([code(x.id), esc(x.triggered_by),
        badge(x.status, ["complete"])])).join("");
  }).catch(() => {});
}
// ---- node detail (the information of the reference's
// ui/app/routes/clients/client: facts, resource utilization meters,
// live allocs, attributes, devices, event history) ------------------
function meter(label, used, total, unit) {
  const pct = total ? Math.min(100, 100 * used / total) : 0;
  return `<div class="tgsum"><b>${esc(label)}</b>
    <div class="bar"><span class="seg running"
      style="width:${pct}%"></span></div>
    <small>${esc(Math.round(used))} / ${esc(Math.round(total))} ${
      esc(unit)} (${Math.round(pct)}%)</small></div>`;
}
function nodeView(id) {
  view(`<h2 id="nh">Node</h2><div id="facts"></div>
    <h2>Resource utilization</h2><div id="res"></div>
    <h2>Allocations</h2><table id="a"></table>
    <h2>Events</h2><table id="ev"></table>
    <h2>Devices</h2><table id="dv"></table>
    <h2>Attributes</h2><table id="at"></table>`);
  let totals = null, lastAllocs = null;
  const renderMeters = allocs => {
    if (allocs) lastAllocs = allocs;
    if (!totals || !lastAllocs) return;
    let cpu = 0, mem = 0, disk = 0;
    for (const a of lastAllocs) {
      if (["complete", "failed", "lost"].includes(a.client_status))
        continue;
      for (const t of Object.values(
          (a.allocated_resources || {}).tasks || {})) {
        cpu += t.cpu || 0; mem += t.memory_mb || 0;
      }
      disk += ((a.allocated_resources || {}).shared || {}).disk_mb
        || 0;
    }
    document.getElementById("res").innerHTML =
      meter("CPU", cpu, totals.cpu, "MHz") +
      meter("Memory", mem, totals.memory_mb, "MiB") +
      meter("Disk", disk, totals.disk_mb, "MiB");
  };
  j(`/v1/node/${id}`).then(n => {
    document.getElementById("nh").textContent = `Node ${n.name}`;
    document.getElementById("facts").innerHTML = kvGrid([
      ["ID", `<code>${esc(n.id)}</code>`],
      ["Status", badge(n.status, ["ready"])],
      ["Datacenter", esc(n.datacenter)],
      ["Class", esc(n.node_class || "<none>")],
      ["Eligibility", esc(n.scheduling_eligibility)],
      ["Drain", esc(n.drain ? "on" : "off")],
      ["Host", esc((n.attributes || {})["unique.network.ip-address"]
        || (n.attributes || {})["unique.hostname"] || "")],
    ]);
    totals = n.node_resources || {};
    document.getElementById("ev").innerHTML =
      row(["Time", "Subsystem", "Message"], "th") +
      (n.events || []).slice().reverse().map(e => row([
        fmtTime(e.timestamp),
        esc(e.subsystem), esc(e.message)])).join("");
    document.getElementById("dv").innerHTML =
      row(["Vendor", "Type", "Name", "Instances"], "th") +
      ((n.node_resources || {}).devices || []).map(d => row([
        esc(d.vendor), esc(d.type), esc(d.name),
        esc((d.instance_ids || []).length)])).join("");
    document.getElementById("at").innerHTML =
      row(["Attribute", "Value"], "th") +
      Object.entries(n.attributes || {}).sort()
        .map(([k, v]) => row([esc(k), `<code>${esc(v)}</code>`]))
        .join("");
    renderMeters(null);  // meters from the livePoll's allocs
  }).catch(e => {
    document.getElementById("facts").innerHTML =
      `<span class="bad">${esc(String(e))}</span>`;
  });
  livePoll(`/v1/node/${id}/allocations`, allocs => {
    document.getElementById("a").innerHTML = allocRows(allocs);
    renderMeters(allocs);
  });
}
// ---- allocation detail (the information of the reference's
// ui/app/routes/allocations/allocation: facts, task states with
// event history, allocated resources, live log tail) ---------------
function allocView(id) {
  view(`<h2 id="ah">Allocation</h2><div id="facts"></div>
    <h2>Tasks</h2><div id="tasks"></div>
    <h2>Allocated resources</h2><table id="res"></table>
    <h2>Logs <small id="logtask"></small></h2><pre id="logs"></pre>`);
  let logTask = null;
  livePoll(`/v1/allocation/${id}`, a => {
    document.getElementById("ah").textContent =
      `Allocation ${a.name || a.id.slice(0, 8)}`;
    document.getElementById("facts").innerHTML = kvGrid([
      ["ID", code(a.id)],
      ["Job", link("/job/" + a.job_id, esc(a.job_id))],
      ["Node", link("/node/" + a.node_id, code(a.node_id))],
      ["Task Group", esc(a.task_group)],
      ["Desired", esc(a.desired_status)],
      ["Client", badge(a.client_status,
        ["running", "complete"])],
      ["Deployment", a.deployment_id
        ? code(a.deployment_id) : ""],
      ["Created", fmtTime(a.create_time)],
    ]);
    const states = a.task_states || {};
    document.getElementById("tasks").innerHTML =
      Object.entries(states).map(([name, st]) => {
        const evs = (st.events || []).slice(-8).map(e =>
          row([
            fmtTime(e.time, true),
            esc(e.type),
            esc(e.display_message || e.message || ""),
          ])
        ).join("");
        return `<div class="tgsum"><b>${esc(name)}</b> ${
          badge(st.state, ["running"])}${
          st.failed ? ' <span class="bad">failed</span>' : ""}
          <table>${row(["Time", "Type", "Description"], "th")}${
            evs}</table></div>`;
      }).join("") || "<small>no task state yet</small>";
    const tasks = (a.allocated_resources || {}).tasks || {};
    const portsOf = nets => (nets || []).flatMap(nw =>
      [...(nw.reserved_ports || []), ...(nw.dynamic_ports || [])]
        .map(p => p.value).filter(Boolean));
    const shared = (a.allocated_resources || {}).shared || {};
    const sharedPorts = [
      ...((shared.ports || []).map(p => p.value)),
      ...portsOf(shared.networks),
    ].filter(Boolean);
    document.getElementById("res").innerHTML =
      row(["Task", "CPU (MHz)", "Memory (MiB)", "Ports"], "th") +
      Object.entries(tasks).map(([name, r]) => row([
        esc(name), esc(r.cpu), esc(r.memory_mb),
        esc(portsOf(r.networks).join(", ")),
      ])).join("") +
      (sharedPorts.length
        ? row(["(group)", "", "",
               esc(sharedPorts.join(", "))])
        : "");
    if (logTask === null) {
      const names = Object.keys(states);
      if (names.length) {
        logTask = names[0];
        document.getElementById("logtask").textContent =
          `(${logTask} stdout)`;
        tailLogs(id, logTask);
      }
    }
  });
}
async function tailLogs(allocId, task) {
  // live chunked tail into the pre, bounded to the last ~16KB.
  // The AbortController is tied to the route generation so a
  // navigation kills the fetch even while read() is parked on an
  // idle stream (otherwise each visit leaks a connection + a server
  // thread until max_idle); the stream auto-reattaches if it ends
  // while the view is still showing this alloc (task restarts).
  const gen = generation;
  const ctl = new AbortController();
  const watchdog = setInterval(() => {
    if (gen !== generation) {
      clearInterval(watchdog);
      ctl.abort();
    }
  }, 500);
  try {
    const r = await fetch(
      `/v1/client/fs/logs/${allocId}?task=${
        encodeURIComponent(task)}&type=stdout&follow=true`,
      {signal: ctl.signal});
    if (!r.ok || !r.body) return;
    const reader = r.body.getReader();
    const dec = new TextDecoder();
    let text = "";
    while (gen === generation) {
      const {done, value} = await reader.read();
      if (done || gen !== generation) break;
      text = (text + dec.decode(value, {stream: true}))
        .slice(-16384);
      const pre = document.getElementById("logs");
      if (!pre) break;
      pre.textContent = text;
    }
    reader.cancel().catch(() => {});
  } catch (e) { /* aborted, or alloc has no client connection */ }
  finally {
    clearInterval(watchdog);
    ctl.abort();
  }
  if (gen === generation) {
    // stream ended while still on this view (restart/GC/idle
    // timeout): reattach after a beat rather than going silently
    // stale under a still-ticking live indicator
    setTimeout(() => {
      if (gen === generation) tailLogs(allocId, task);
    }, 2000);
  }
}

// ---- router --------------------------------------------------------
function route() {
  generation += 1;
  const h = location.hash || "#/jobs";
  let m;
  if ((m = h.match(/^#\\/job\\/(.+)$/))) return jobView(m[1]);
  if ((m = h.match(/^#\\/node\\/(.+)$/))) return nodeView(m[1]);
  if ((m = h.match(/^#\\/alloc\\/(.+)$/))) return allocView(m[1]);
  if (h === "#/nodes") return nodesView();
  if (h === "#/allocs") return allocsView();
  return jobsView();
}
window.addEventListener("hashchange", route);
j("/v1/status/leader").then(l => {
  document.getElementById("leader").textContent =
    "leader: " + JSON.stringify(l);
}).catch(() => {});
route();
</script>
</body>
</html>
"""
