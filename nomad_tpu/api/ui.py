"""Built-in web UI (the parity nod to the reference's Ember app under
ui/ — same data, one self-contained page against the /v1 API).

Live updates ride the API's blocking queries: each list view long-polls
its endpoint with ?index=N&wait=30 (reference rpc.go:780 blockingRPC;
the Ember UI's live updates poll the same way) and re-renders only when
the X-Nomad-Index advances.  Hash routes provide drill-down detail:
#/jobs, #/job/<id>, #/nodes, #/node/<id>, #/allocs, #/alloc/<id>.
Served at /ui by the HTTP server.
"""

UI_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<style>
  :root { color-scheme: light dark; }
  body { font-family: system-ui, sans-serif; margin: 2rem;
         max-width: 76rem; }
  h1 { font-size: 1.3rem; }
  h2 { font-size: 1.05rem; margin-top: 1.6rem; }
  nav a { margin-right: 1rem; }
  table { border-collapse: collapse; width: 100%; font-size: .85rem; }
  th, td { text-align: left; padding: .3rem .6rem;
           border-bottom: 1px solid #8884; }
  code, pre { font-size: .8rem; }
  pre { background: #8881; padding: .6rem; overflow-x: auto; }
  .ok  { color: #2a9d2a; }
  .bad { color: #d43a3a; }
  #err { color: #d43a3a; }
  #live { font-size: .75rem; opacity: .6; }
</style>
</head>
<body>
<h1>nomad-tpu <small id="leader"></small> <span id="live"></span></h1>
<nav>
  <a href="#/jobs">Jobs</a><a href="#/nodes">Nodes</a
  ><a href="#/allocs">Allocations</a>
</nav>
<div id="err"></div>
<div id="view"></div>
<script>
function esc(v) {
  return String(v ?? "").replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;",
  })[c]);
}
function row(cells, tag) {
  return "<tr>" + cells.map(c => `<${tag||"td"}>${c}</${tag||"td"}>`)
    .join("") + "</tr>";
}
function link(href, text) {
  // href is attacker-influenced (job ids): escape for the attribute
  return `<a href="#${esc(href)}">${text}</a>`;
}
function code(v) { return `<code>${esc(v).slice(0, 8)}</code>`; }
function badge(s, good) {
  return `<span class="${good.includes(s) ? "ok" : "bad"}">` +
    esc(s) + "</span>";
}
async function j(p) {
  const r = await fetch(p);
  if (!r.ok) throw new Error(p + ": " + r.status);
  return r.json();
}

// ---- blocking-query live poller -----------------------------------
// one generation per route; switching routes abandons the old loop
let generation = 0;
async function livePoll(path, render) {
  const gen = generation;
  let index = 0;
  while (gen === generation) {
    try {
      const url = index
        ? `${path}${path.includes("?") ? "&" : "?"}index=${index}&wait=30`
        : path;
      const r = await fetch(url);
      if (!r.ok) throw new Error(path + ": " + r.status);
      const next = parseInt(r.headers.get("X-Nomad-Index") || "0");
      const data = await r.json();
      if (gen !== generation) return;
      render(data);
      document.getElementById("err").textContent = "";
      document.getElementById("live").textContent =
        "live (index " + next + ")";
      index = next || index;
      if (!next) await new Promise(res => setTimeout(res, 2000));
    } catch (e) {
      if (gen !== generation) return;
      document.getElementById("err").textContent = String(e);
      await new Promise(res => setTimeout(res, 2000));
    }
  }
}
function view(html) { document.getElementById("view").innerHTML = html; }

// ---- views ---------------------------------------------------------
function jobsView() {
  view('<h2>Jobs</h2><table id="t"></table>');
  livePoll("/v1/jobs", jobs => {
    document.getElementById("t").innerHTML =
      row(["ID","Type","Priority","Status"], "th") +
      jobs.map(x => row([link("/job/" + x.ID, esc(x.ID)), esc(x.Type),
        esc(x.Priority),
        badge(x.Status, ["running","complete"])])).join("");
  });
}
function nodesView() {
  view('<h2>Nodes</h2><table id="t"></table>');
  livePoll("/v1/nodes", nodes => {
    document.getElementById("t").innerHTML =
      row(["ID","Name","DC","Status","Eligibility"], "th") +
      nodes.map(x => row([
        link("/node/" + x.ID, code(x.ID)), esc(x.Name),
        esc(x.Datacenter), badge(x.Status, ["ready"]),
        esc(x.SchedulingEligibility)])).join("");
  });
}
function allocRows(allocs) {
  return row(["ID","Job","Group","Node","Desired","Client"], "th") +
    allocs.map(x => row([
      link("/alloc/" + x.id, code(x.id)),
      link("/job/" + x.job_id, esc(x.job_id)),
      esc(x.task_group),
      link("/node/" + x.node_id, code(x.node_id)),
      esc(x.desired_status),
      badge(x.client_status, ["running","complete"])])).join("");
}
function allocsView() {
  view('<h2>Allocations</h2><table id="t"></table>');
  livePoll("/v1/allocations", allocs => {
    document.getElementById("t").innerHTML = allocRows(allocs);
  });
}
function jobView(id) {
  view(`<h2>Job ${esc(id)}</h2><pre id="d"></pre>
    <h2>Allocations</h2><table id="a"></table>
    <h2>Evaluations</h2><table id="e"></table>
    <h2>Deployments</h2><table id="dep"></table>`);
  j(`/v1/job/${id}`).then(job => {
    document.getElementById("d").textContent =
      JSON.stringify(job, null, 1).slice(0, 4000);
  }).catch(() => {});
  j(`/v1/job/${id}/evaluations`).then(evs => {
    document.getElementById("e").innerHTML =
      row(["ID","TriggeredBy","Status"], "th") +
      evs.map(x => row([code(x.id), esc(x.triggered_by),
        badge(x.status, ["complete"])])).join("");
  }).catch(() => {});
  j(`/v1/job/${id}/deployments`).then(ds => {
    document.getElementById("dep").innerHTML =
      row(["ID","Version","Status"], "th") +
      ds.map(x => row([code(x.id), esc(x.job_version),
        badge(x.status, ["successful","running"])])).join("");
  }).catch(() => {});
  livePoll(`/v1/job/${id}/allocations`, allocs => {
    document.getElementById("a").innerHTML = allocRows(allocs);
  });
}
function nodeView(id) {
  view(`<h2>Node ${esc(id).slice(0,8)}</h2><pre id="d"></pre>
    <h2>Allocations</h2><table id="a"></table>`);
  j(`/v1/node/${id}`).then(n => {
    document.getElementById("d").textContent =
      JSON.stringify(n, null, 1).slice(0, 4000);
  }).catch(() => {});
  livePoll(`/v1/node/${id}/allocations`, allocs => {
    document.getElementById("a").innerHTML = allocRows(allocs);
  });
}
function allocView(id) {
  view(`<h2>Allocation ${esc(id).slice(0,8)}</h2><pre id="d"></pre>`);
  livePoll(`/v1/allocation/${id}`, a => {
    document.getElementById("d").textContent =
      JSON.stringify(a, null, 1).slice(0, 8000);
  });
}

// ---- router --------------------------------------------------------
function route() {
  generation += 1;
  const h = location.hash || "#/jobs";
  let m;
  if ((m = h.match(/^#\\/job\\/(.+)$/))) return jobView(m[1]);
  if ((m = h.match(/^#\\/node\\/(.+)$/))) return nodeView(m[1]);
  if ((m = h.match(/^#\\/alloc\\/(.+)$/))) return allocView(m[1]);
  if (h === "#/nodes") return nodesView();
  if (h === "#/allocs") return allocsView();
  return jobsView();
}
window.addEventListener("hashchange", route);
j("/v1/status/leader").then(l => {
  document.getElementById("leader").textContent =
    "leader: " + JSON.stringify(l);
}).catch(() => {});
route();
</script>
</body>
</html>
"""
