"""HTTP API (reference command/agent/http.go:252-327 route table).

Serves the `/v1/*` surface over the in-process server: jobs (list,
register, read, delete, evaluations, allocations, plan, scale,
periodic force), nodes (list, read, drain, eligibility), allocations,
evaluations, deployments (+promote/fail/pause), operator scheduler
configuration (incl. the TPU-backend toggle), agent info/members, status
leader, search, system gc, and metrics.

ACL enforcement: when the server has ACLs enabled, every request resolves
its X-Nomad-Token header to a policy set and is checked against the
namespace capability the route requires (reference nomad/acl.go).
"""
from __future__ import annotations

import json
import os
import re
import threading
from dataclasses import replace as dc_replace
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlencode, urlparse

from ..structs import DrainStrategy, SchedulerConfiguration, PreemptionConfig
from .codec import (
    alloc_to_dict,
    deployment_to_dict,
    eval_to_dict,
    job_from_dict,
    job_to_dict,
    node_to_dict,
    csi_plugin_to_dict,
    csi_volume_from_dict,
    csi_volume_stub,
    csi_volume_to_dict,
    scaling_event_to_dict,
    scaling_policy_stub,
    scaling_policy_to_dict,
)


# data GET endpoints eligible for ?index= blocking queries
_BLOCKING_PREFIXES = (
    "/v1/jobs",
    "/v1/job/",
    "/v1/nodes",
    "/v1/node/",
    "/v1/allocations",
    "/v1/allocation/",
    "/v1/evaluations",
    "/v1/evaluation/",
    "/v1/deployments",
    "/v1/deployment/",
    "/v1/volumes",
    "/v1/volume/",
    "/v1/catalog/",
)


class HTTPError(Exception):
    def __init__(self, code: int, message: str) -> None:
        super().__init__(message)
        self.code = code


def _fed_proxy_timeout_s() -> float:
    """Deadline for a ?region= read proxied to another region's
    advertised HTTP address — a wedged remote region must cost the
    caller a bounded wait, never a pinned thread."""
    try:
        return max(
            0.1,
            float(
                os.environ.get("NOMAD_TPU_FED_PROXY_TIMEOUT_S", "2")
            ),
        )
    except ValueError:
        return 2.0


class APIHandler(BaseHTTPRequestHandler):
    server_ref = None  # class attr set by start_http_server
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence default logging
        pass

    # -- plumbing -------------------------------------------------------

    def _consume_body(self) -> None:
        """Drain the request body exactly once, at dispatch entry.

        With HTTP/1.1 keep-alive, a handler that responds without
        reading its request body leaves those bytes in the stream —
        the NEXT request parse then reads ``{}`` as a request line
        and answers 501, poisoning every other request on a
        persistent connection (found by the swarm harness, whose
        generators hold one connection per worker; urllib-based
        tests reconnect per request and never hit it).  Draining up
        front also lets the overload shed path answer 429 without
        the connection-corruption tax."""
        length = int(self.headers.get("Content-Length") or 0)
        self._raw_body = self.rfile.read(length) if length > 0 else b""

    def _body(self) -> Dict:
        raw = getattr(self, "_raw_body", b"")
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except ValueError as exc:
            raise HTTPError(400, f"invalid JSON body: {exc}")

    def _respond(self, payload: Any, code: int = 200) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        index = getattr(self, "_reply_index", None)
        if index is not None:
            self.send_header("X-Nomad-Index", str(index))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, message: str) -> None:
        self._respond({"error": message}, code)

    def _stream_chunked(
        self, frames, content_type: str = "application/octet-stream"
    ) -> None:
        """HTTP/1.1 chunked streaming: one chunk per yielded bytes
        value, until the generator ends or the consumer disconnects
        (the streaming-transport analog of the reference's yamux
        frames for logs -f / agent monitor)."""
        import select as _select
        import socket as _socket

        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Nomad-Stream", "chunked")
        self.end_headers()
        try:
            for data in frames:
                if not data:
                    # idle tick: a consumer that hung up must not pin
                    # this thread for the stream's max lifetime — a
                    # readable socket that yields no bytes is EOF
                    r, _w, _x = _select.select(
                        [self.connection], [], [], 0
                    )
                    if r:
                        try:
                            peek = self.connection.recv(
                                1, _socket.MSG_PEEK
                            )
                        except OSError:
                            return
                        if not peek:
                            return
                    continue
                self.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n"
                )
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.close_connection = True

    def _serve_exec_websocket(self, handle) -> None:
        """Bridge an ExecStreamHandle onto the upgraded connection:
        inbound frames carry stdin/tty_size, outbound frames carry
        stdout/stderr and the final exited/result."""
        import base64 as _b64
        import queue as _queue
        import threading as _threading

        from . import ws as _ws

        if not _ws.server_handshake(self):
            raise HTTPError(400, "websocket handshake failed")
        self.close_connection = True
        sock = self.connection
        done = _threading.Event()
        # one writer at a time: the reader thread answers PINGs on
        # the same socket the output pump writes to — interleaved
        # sendalls would corrupt the frame stream
        send_lock = _threading.Lock()

        def send(op, payload) -> None:
            with send_lock:
                _ws.write_frame(sock, op, payload)

        def reader() -> None:
            try:
                while not done.is_set():
                    frame = _ws.read_frame(self.rfile)
                    op, payload = frame
                    if op == _ws.OP_CLOSE:
                        handle.terminate()
                        return
                    if op == _ws.OP_PING:
                        send(_ws.OP_PONG, payload)
                        continue
                    try:
                        msg = json.loads(payload.decode("utf-8"))
                    except ValueError:
                        continue
                    stdin = msg.get("stdin") or {}
                    if stdin.get("data"):
                        handle.write_stdin(
                            _b64.b64decode(stdin["data"])
                        )
                    if stdin.get("close"):
                        handle.close_stdin()
                    tty = msg.get("tty_size") or {}
                    if tty:
                        handle.resize(
                            int(tty.get("height", 0)),
                            int(tty.get("width", 0)),
                        )
            except (ConnectionError, OSError, ValueError):
                handle.terminate()

        _threading.Thread(target=reader, daemon=True).start()
        try:
            while True:
                try:
                    event = handle.read_event(timeout=0.25)
                except _queue.Empty:
                    continue
                if event is None:
                    break
                stream, data = event
                send(
                    _ws.OP_TEXT,
                    json.dumps(
                        {
                            stream: {
                                "data": _b64.b64encode(
                                    data
                                ).decode("ascii")
                            }
                        }
                    ).encode("utf-8"),
                )
            try:
                code = handle.wait(timeout=10)
            except Exception:  # noqa: BLE001 — report, don't hang
                handle.terminate()
                code = -1
            send(
                _ws.OP_TEXT,
                json.dumps(
                    {
                        "exited": True,
                        "result": {"exit_code": code},
                    }
                ).encode("utf-8"),
            )
            send(_ws.OP_CLOSE, b"")
        except (ConnectionError, OSError):
            handle.terminate()
        finally:
            done.set()

    def _check_acl(self, capability: str, namespace: str = "default"):
        self._check_acl_any((capability,), namespace)

    def _check_acl_any(self, capabilities, namespace: str = "default"):
        """Pass if the token holds ANY of the capabilities (reference
        endpoints often accept e.g. scale-job OR submit-job)."""
        srv = self.server_ref
        acls = getattr(srv, "acls", None)
        if acls is None or not acls.enabled:
            return
        token = self.headers.get("X-Nomad-Token", "")
        if not any(
            acls.allowed(token, namespace, c) for c in capabilities
        ):
            raise HTTPError(403, "Permission denied")

    @staticmethod
    def _cluster_obs(
        srv, what: str, params: dict, region: Optional[str] = None
    ) -> dict:
        """Cluster observability fan-in when the server is
        cluster-capable; a single-process Server answers with its
        local share in the same merged shape.  The fan-in is
        region-local by construction — an explicit ``region``
        (the ?region= escape hatch) forwards the whole query to that
        region's leader and counts a federation.wan_reads."""
        regional = getattr(srv, "cluster_query_region", None)
        if regional is not None:
            return regional(what, params, region=region)
        query = getattr(srv, "cluster_query", None)
        if query is not None:
            return query(what, params)
        return {
            "servers": {"local": srv._obs_local(what, params)},
            "asked": 1,
            "unreachable": 0,
        }

    # -- dispatch -------------------------------------------------------

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PUT(self):
        self._dispatch("PUT")

    def do_DELETE(self):
        self._dispatch("DELETE")

    def _shed(self, retry_after_s: float, mode: int) -> None:
        """429 + Retry-After: the backpressure half of the overload
        ladder.  Clients (the CLI, the swarm harness, any
        well-behaved SDK) back off for Retry-After seconds and retry
        — bounded sheds absorb the overload instead of an unbounded
        broker backlog absorbing the p99.

        On a federated server, the shed also names the nearest
        healthy OTHER region (X-Nomad-Retry-Region, with one of its
        advertised HTTP addresses) derived from gossip health — a
        redirect-aware client moves its traffic to the next region
        instead of hammering this dying one."""
        from ..server.overload import MODE_NAMES

        body = {
            "error": "server overloaded",
            "Mode": MODE_NAMES[mode],
            "RetryAfter": retry_after_s,
        }
        hint = None
        fed = getattr(self.server_ref, "federation", None)
        if fed is not None:
            try:
                hint = fed.nearest_healthy_region()
            except Exception:  # noqa: BLE001 — hint is best-effort
                hint = None
        if hint is not None:
            region, http_addr = hint
            body["RetryRegion"] = region
            body["RetryRegionAddr"] = http_addr
            metrics = getattr(self.server_ref, "metrics", None)
            if metrics is not None:
                metrics.incr("federation.shed_redirects")
        data = json.dumps(body).encode()
        self.send_response(429)
        self.send_header("Content-Type", "application/json")
        self.send_header(
            "Retry-After", str(max(1, int(round(retry_after_s))))
        )
        if hint is not None:
            self.send_header("X-Nomad-Retry-Region", hint[0])
            if hint[1]:
                self.send_header(
                    "X-Nomad-Retry-Region-Addr", hint[1]
                )
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _dispatch(self, method: str) -> None:
        url = urlparse(self.path)
        path = url.path.rstrip("/")
        query = {k: v[0] for k, v in parse_qs(url.query).items()}
        try:
            self._consume_body()
            # ingress backpressure (server/overload.py): admission by
            # priority class BEFORE any state read or body parse —
            # heartbeats > plan/blocking queries > job submissions.
            # Shed requests cost the server one classify + one
            # counter, which is the whole point.
            ctl = getattr(self.server_ref, "overload", None)
            if ctl is not None:
                from ..server.overload import classify_request

                admitted, retry_after = ctl.admit(
                    classify_request(method, path)
                )
                if not admitted:
                    self._shed(retry_after, ctl.mode)
                    return
            # the ?region= escape hatch: reads stay region-local by
            # default; an EXPLICIT foreign region proxies the GET to
            # that region's advertised HTTP address and counts a
            # federation.wan_reads.  /v1/cluster/* keeps its own
            # transport-level forward (works without remote HTTP
            # listeners), so it is excluded here.
            region = query.get("region")
            srv = self.server_ref
            if (
                method == "GET"
                and region
                and region != getattr(srv, "region", region)
                and getattr(srv, "federation", None) is not None
                and not path.startswith("/v1/cluster")
            ):
                self._proxy_region(region, path, query)
                return
            # blocking queries (reference rpc.go:780 blockingRPC): a GET
            # with ?index=N long-polls until the state advances past N
            # (or the wait expires), then responds with fresh data; the
            # X-Nomad-Index response header feeds the next poll.
            # Restricted to known data endpoints, and — with ACLs on —
            # to requests whose token resolves, so unauthenticated or
            # bogus requests can't pin server threads for the wait.
            if (
                method == "GET"
                and "index" in query
                and path.startswith(_BLOCKING_PREFIXES)
            ):
                acls = getattr(self.server_ref, "acls", None)
                authed = not (acls is not None and acls.enabled) or (
                    acls.resolve(
                        self.headers.get("X-Nomad-Token", "")
                    )
                    is not None
                )
                try:
                    min_index = int(query["index"]) + 1
                    wait_s = min(
                        float(query.get("wait", "5")), 60.0
                    )
                except ValueError:
                    raise HTTPError(400, "bad index/wait")
                if authed and ctl is not None:
                    # degradation rung between "served" and "shed":
                    # at SHEDDING+, long-polls answer immediately
                    # (current state, X-Nomad-Index intact) instead
                    # of pinning a server thread for the wait
                    wait_s = ctl.blocking_wait_budget(wait_s)
                if authed and wait_s > 0:
                    self.server_ref.store.wait_for_index(
                        min_index, timeout=wait_s
                    )
            # capture the reply index BEFORE the handler reads state:
            # a concurrent write between read and respond must re-wake
            # the next poll rather than be skipped past
            try:
                self._reply_index = (
                    self.server_ref.store.latest_index()
                )
            except Exception:  # noqa: BLE001
                self._reply_index = None
            handled = self._route(method, path, query)
            if not handled:
                self._error(404, f"no handler for {method} {path}")
        except HTTPError as exc:
            self._error(exc.code, str(exc))
        except (KeyError, ValueError) as exc:
            self._error(400, str(exc))
        except Exception as exc:  # noqa: BLE001
            self._error(500, f"{type(exc).__name__}: {exc}")

    def _proxy_region(
        self, region: str, path: str, query: Dict[str, str]
    ) -> None:
        """Forward one GET to ``region``'s advertised HTTP address
        (learned through WAN gossip) and relay the answer verbatim —
        the explicit WAN read the federation.wan_reads counter
        accounts for."""
        import urllib.error
        import urllib.request

        srv = self.server_ref
        target = srv.federation.http_addr_in(region)
        if target is None:
            raise HTTPError(
                502, f"no HTTP address known in region {region!r}"
            )
        metrics = getattr(srv, "metrics", None)
        if metrics is not None:
            metrics.incr("federation.wan_reads")
        qs = urlencode(
            {k: v for k, v in query.items() if k != "region"}
        )
        url = f"http://{target}{path}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, method="GET")
        token = self.headers.get("X-Nomad-Token")
        if token:
            req.add_header("X-Nomad-Token", token)
        try:
            with urllib.request.urlopen(
                req, timeout=_fed_proxy_timeout_s()
            ) as resp:
                code = resp.status
                ctype = resp.headers.get(
                    "Content-Type", "application/json"
                )
                data = resp.read()
        except urllib.error.HTTPError as exc:
            code = exc.code
            ctype = exc.headers.get(
                "Content-Type", "application/json"
            )
            data = exc.read()
        except (OSError, urllib.error.URLError) as exc:
            raise HTTPError(
                502, f"region {region!r} proxy failed: {exc}"
            )
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("X-Nomad-Proxied-Region", region)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # -- routes (reference http.go registerHandlers) --------------------

    def _route(self, method: str, path: str, q: Dict[str, str]) -> bool:
        srv = self.server_ref
        store = srv.store
        ns = q.get("namespace", "default")

        if path in ("/ui", "/ui/index.html", "") and method == "GET":
            # built-in single-page UI (the reference ships an Ember
            # app under ui/; same /v1 data)
            from .ui import UI_HTML

            body = UI_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return True

        if path == "/v1/jobs":
            if method == "GET":
                self._check_acl("read-job", ns)
                prefix = q.get("prefix", "")
                jobs = [
                    {
                        "ID": j.id,
                        "Name": j.name,
                        "Type": j.type,
                        "Priority": j.priority,
                        "Status": store.derive_job_status(j.namespace, j.id),
                        "Namespace": j.namespace,
                    }
                    for j in store.iter_jobs()
                    if j.id.startswith(prefix)
                ]
                self._respond(jobs)
                return True
            if method in ("POST", "PUT"):
                self._check_acl("submit-job", ns)
                body = self._body()
                raw_job = body.get("Job") or body.get("job") or body
                job = job_from_dict(raw_job)
                ev = srv.register_job(job)
                self._respond(
                    {"EvalID": ev.id if ev else "", "JobModifyIndex": job.modify_index}
                )
                return True

        m = re.fullmatch(r"/v1/job/([^/]+)", path)
        if m:
            job_id = m.group(1)
            if method == "GET":
                self._check_acl("read-job", ns)
                job = store.job_by_id(ns, job_id)
                if job is None:
                    raise HTTPError(404, "job not found")
                d = job_to_dict(job)
                d["status"] = store.derive_job_status(ns, job_id)
                self._respond(d)
                return True
            if method in ("POST", "PUT"):
                self._check_acl("submit-job", ns)
                body = self._body()
                raw_job = body.get("Job") or body.get("job") or body
                job = job_from_dict(raw_job)
                job.id = job_id
                ev = srv.register_job(job)
                self._respond({"EvalID": ev.id if ev else ""})
                return True
            if method == "DELETE":
                self._check_acl("submit-job", ns)
                purge = q.get("purge", "false") == "true"
                ev = srv.deregister_job(ns, job_id, purge=purge)
                self._respond({"EvalID": ev.id if ev else ""})
                return True

        if path == "/v1/jobs/parse" and method in ("POST", "PUT"):
            # HCL -> canonical JSON job (reference jobs_endpoint.go
            # /v1/jobs/parse)
            self._check_acl("submit-job", ns)
            from ..jobspec import ParseError, parse as parse_hcl

            body = self._body()
            try:
                job = parse_hcl(body.get("JobHCL", ""))
            except ParseError as exc:
                raise HTTPError(400, str(exc))
            self._respond(job_to_dict(job))
            return True

        if path == "/v1/validate/job" and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            body = self._body()
            raw_job = body.get("Job") or body.get("job") or body
            try:
                job = job_from_dict(raw_job)
                srv.validate_job(job)
            except (ValueError, KeyError) as exc:
                self._respond(
                    {
                        "Error": str(exc),
                        "ValidationErrors": [str(exc)],
                        "Warnings": "",
                    }
                )
                return True
            self._respond({"ValidationErrors": [], "Warnings": ""})
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/versions", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            versions = store.versions_of_job(ns, m.group(1))
            if not versions:
                raise HTTPError(404, "job not found")
            self._respond(
                {
                    "Versions": [job_to_dict(j) for j in versions],
                    "Diffs": [],
                }
            )
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/revert", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            body = self._body()
            try:
                ev = srv.revert_job(
                    ns,
                    m.group(1),
                    int(body.get("JobVersion", 0)),
                    enforce_prior_version=body.get(
                        "EnforcePriorVersion"
                    ),
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            self._respond({"EvalID": ev.id if ev else ""})
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/stable", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            body = self._body()
            try:
                srv.set_job_stability(
                    ns,
                    m.group(1),
                    int(body.get("JobVersion", 0)),
                    bool(body.get("Stable", True)),
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond({"Index": store.latest_index()})
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/summary", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            try:
                self._respond(srv.job_summary(ns, m.group(1)))
            except KeyError:
                raise HTTPError(404, "job not found")
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/federation", path)
        if m and method == "GET":
            # per-region registration/placement status of a federated
            # job: the local region answers from local state, every
            # other region in the job's Multiregion block is asked
            # live over region_call
            self._check_acl("read-job", ns)
            fed = getattr(srv, "federation", None)
            if fed is None:
                raise HTTPError(
                    400, "server is not federation-capable"
                )
            try:
                self._respond(fed.federation_status(ns, m.group(1)))
            except KeyError:
                raise HTTPError(404, "job not found")
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/evaluations", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            self._respond(
                [eval_to_dict(e) for e in store.evals_by_job(ns, m.group(1))]
            )
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/allocations", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            self._respond(
                [
                    alloc_to_dict(a)
                    for a in store.allocs_by_job(ns, m.group(1))
                ]
            )
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/deployments", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            self._respond(
                [
                    deployment_to_dict(d)
                    for d in store.deployments_by_job(ns, m.group(1))
                ]
            )
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/plan", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            body = self._body()
            raw_job = body.get("Job") or body.get("job") or body
            job = job_from_dict(raw_job)
            job.id = m.group(1)
            self._respond(
                srv.plan_job(job, diff=body.get("Diff", True))
            )
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/dispatch", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("dispatch-job", ns)
            body = self._body()
            # Payload arrives base64-encoded (api.Job Payload contract)
            import base64

            # tolerate line-wrapped base64 (Go's decoder skips \r\n)
            raw_payload = "".join((body.get("Payload") or "").split())
            try:
                payload = (
                    base64.b64decode(raw_payload, validate=True) or None
                )
            except (ValueError, TypeError):
                raise HTTPError(400, "Payload must be base64")
            child = srv.dispatch_job(
                ns,
                m.group(1),
                meta=body.get("Meta") or body.get("meta"),
                payload=payload,
            )
            self._respond({"DispatchedJobID": child.id})
            return True

        m = re.fullmatch(r"/v1/client/fs/logs/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("read-logs", ns)
            task = q.get("task", "")
            kind = q.get("type", "stdout")
            if q.get("follow") == "true":
                # chunked live tail (reference client fs streaming
                # for `alloc logs -f`); raw bytes, ends when the
                # consumer disconnects.  Validate BEFORE the 200 —
                # a typo'd alloc must 404, not stream emptiness
                alloc_id = m.group(1)
                try:
                    first, cursor0 = srv.tail_task_log(
                        alloc_id, task, kind, None
                    )
                except KeyError as exc:
                    raise HTTPError(404, str(exc))

                def frames():
                    import time as _time

                    cursor = cursor0
                    if first:
                        yield first
                    idle = 0.0
                    max_idle = float(q.get("max_idle", "3600"))
                    while idle < max_idle:
                        try:
                            data, cursor = srv.tail_task_log(
                                alloc_id, task, kind, cursor
                            )
                        except KeyError:
                            return
                        if data:
                            idle = 0.0
                            yield data
                        else:
                            idle += 0.25
                            _time.sleep(0.25)
                            yield b""  # liveness probe tick

                self._stream_chunked(
                    frames(), "application/octet-stream"
                )
                return True
            try:
                data = srv.read_task_log(m.group(1), task, kind)
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond({"Data": data.decode("utf-8", "replace")})
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/evaluate", path)
        if m and method in ("POST", "PUT"):
            # force a fresh evaluation (reference nomad/job_endpoint.go
            # Job.Evaluate; command/job_eval.go)
            self._check_acl("submit-job", ns)
            job = store.job_by_id(ns, m.group(1))
            if job is None:
                raise HTTPError(404, "job not found")
            if job.is_periodic() or job.is_parameterized():
                # templates never evaluate directly (reference
                # job_endpoint.go Evaluate rejects both)
                raise HTTPError(
                    400,
                    "can't evaluate periodic/parameterized job",
                )
            from ..structs import Evaluation

            ev = Evaluation(
                namespace=job.namespace,
                priority=job.priority,
                type=job.type,
                triggered_by="job-eval",
                job_id=job.id,
                status="pending",
            )
            store.upsert_evals([ev])
            srv.on_eval_update(ev)
            self._respond({"EvalID": ev.id})
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/periodic/force", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            job = store.job_by_id(ns, m.group(1))
            if job is None or not job.is_periodic():
                raise HTTPError(404, "periodic job not found")
            child = srv.periodic.force_launch(job)
            self._respond({"JobID": child.id})
            return True

        m = re.fullmatch(r"/v1/job/([^/]+)/scale", path)
        if m and method in ("POST", "PUT"):
            # reference nomad/job_endpoint.go Job.Scale; count=None is
            # the autoscaler status-report path (event only)
            self._check_acl_any(("scale-job", "submit-job"), ns)
            body = self._body()
            target = body.get("Target", {}) or {}
            group = target.get("Group") or body.get("group")
            count = body.get("Count", body.get("count"))
            try:
                ev, _event = srv.scale_job(
                    ns,
                    m.group(1),
                    group,
                    count=count,
                    message=body.get("Message", ""),
                    error=bool(body.get("Error", False)),
                    meta=body.get("Meta") or {},
                    policy_override=bool(body.get("PolicyOverride", False)),
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond({"EvalID": ev.id if ev else ""})
            return True

        if m and method == "GET":
            # JobScaleStatusResponse (reference job_endpoint.go
            # ScaleStatus): per-group desired/placed/running counts +
            # retained scaling events
            self._check_acl_any(("read-job-scaling", "read-job"), ns)
            job = store.job_by_id(ns, m.group(1))
            if job is None:
                raise HTTPError(404, "job not found")
            events = store.scaling_events_for_job(ns, job.id)
            live_by_group: Dict[str, list] = {}
            for a in store.allocs_by_job(ns, job.id):
                if not a.terminal_status():
                    live_by_group.setdefault(a.task_group, []).append(a)
            groups = {}
            for tg in job.task_groups:
                allocs = live_by_group.get(tg.name, [])
                groups[tg.name] = {
                    "Desired": tg.count,
                    "Placed": len(allocs),
                    "Running": sum(
                        1 for a in allocs if a.client_status == "running"
                    ),
                    "Events": [
                        scaling_event_to_dict(e)
                        for e in events.get(tg.name, [])
                    ],
                }
            self._respond(
                {
                    "JobID": job.id,
                    "Namespace": job.namespace,
                    "JobStopped": job.stop,
                    "TaskGroups": groups,
                }
            )
            return True

        if path == "/v1/scaling/policies" and method == "GET":
            # listing is scoped to the ACL-checked namespace; no
            # cross-namespace enumeration
            self._check_acl("list-scaling-policies", ns)
            pols = store.iter_scaling_policies(
                namespace=ns, job_id=q.get("job")
            )
            self._respond(
                [scaling_policy_stub(p) for p in pols]
            )
            return True

        m = re.fullmatch(r"/v1/scaling/policy/([^/]+)", path)
        if m and method == "GET":
            pol = store.scaling_policy_by_id(m.group(1))
            if pol is None:
                raise HTTPError(404, "scaling policy not found")
            # authorize against the namespace the policy lives in
            self._check_acl(
                "read-scaling-policy", pol.target_tuple()[0] or ns
            )
            self._respond(scaling_policy_to_dict(pol))
            return True

        if path == "/v1/nodes" and method == "GET":
            self._check_acl("node:read")
            prefix = q.get("prefix", "")
            self._respond(
                [
                    {
                        "ID": n.id,
                        "Name": n.name,
                        "Datacenter": n.datacenter,
                        "Status": n.status,
                        "SchedulingEligibility": n.scheduling_eligibility,
                        "Drain": n.drain,
                    }
                    for n in store.iter_nodes()
                    if n.id.startswith(prefix)
                ]
            )
            return True

        m = re.fullmatch(r"/v1/node/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("node:read")
            node = store.node_by_id(m.group(1))
            if node is None:
                raise HTTPError(404, "node not found")
            self._respond(node_to_dict(node))
            return True

        m = re.fullmatch(r"/v1/node/([^/]+)/allocations", path)
        if m and method == "GET":
            self._check_acl("node:read")
            self._respond(
                [alloc_to_dict(a) for a in store.allocs_by_node(m.group(1))]
            )
            return True

        if path == "/v1/node/register" and method in ("POST", "PUT"):
            # remote node registration (reference Node.Register RPC;
            # lets client agents attach to a networked cluster over
            # the HTTP surface — forwarding routes it to the leader)
            self._check_acl("node:write")
            from .codec import node_from_dict

            node = node_from_dict(
                self._body().get("Node") or self._body()
            )
            if not node.id:
                raise HTTPError(400, "missing node id")
            srv.register_node(node)
            self._respond(
                {"HeartbeatTTL": getattr(srv, "heartbeat_ttl", 0)}
            )
            return True

        if path == "/v1/client/register" and method in (
            "POST", "PUT",
        ):
            # a REMOTE client announces its callback endpoint; the
            # server proxies fs/exec/logs for its allocs through it
            # (reference nomad/client_rpc.go NodeRpc topology)
            self._check_acl("node:write")
            body = self._body()
            node_id = body.get("NodeID") or body.get("node_id", "")
            addr = body.get("Addr") or body.get("addr", "")
            if not node_id or not addr:
                raise HTTPError(400, "NodeID and Addr required")
            from ..client.remote import HTTPClientProxy

            srv.register_client(node_id, HTTPClientProxy(addr))
            self._respond({})
            return True

        m = re.fullmatch(r"/v1/node/([^/]+)/heartbeat", path)
        if m and method in ("POST", "PUT"):
            # (reference Node.UpdateStatus keepalive)
            self._check_acl("node:write")
            try:
                srv.heartbeat(m.group(1))
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond({})
            return True

        m = re.fullmatch(r"/v1/node/([^/]+)/allocs", path)
        if m and method in ("POST", "PUT"):
            # client pushes alloc status transitions (reference
            # Node.UpdateAlloc)
            self._check_acl("node:write")
            body = self._body()
            updates = []
            for raw in body.get("Allocs") or []:
                if "task_states" in raw or (
                    "allocated_resources" in raw
                ):
                    # full wire-form update from a remote client.
                    # Merge ONLY the client-owned fields onto the
                    # server's canonical alloc: the client's copy of
                    # desired_status/desired_transition/deployment_id
                    # is stale by construction (a drain/preempt/stop
                    # staged since its last pull must not be
                    # reverted by a task-state push) — reference
                    # Node.UpdateAlloc persists client state, never
                    # scheduler intent
                    from .codec import alloc_from_dict

                    full = alloc_from_dict(raw)
                    existing = store.alloc_by_id(full.id)
                    if existing is None:
                        continue
                    updates.append(
                        dc_replace(
                            existing,
                            client_status=full.client_status,
                            client_description=(
                                full.client_description
                            ),
                            task_states=full.task_states,
                            deployment_status=(
                                full.deployment_status
                            ),
                            modify_time=full.modify_time,
                        )
                    )
                    continue
                alloc = store.alloc_by_id(
                    raw.get("ID") or raw.get("id", "")
                )
                if alloc is None:
                    continue
                status = raw.get("ClientStatus") or raw.get(
                    "client_status"
                )
                # Never mutate the store's canonical object: the upsert
                # computes was_live from the *existing* entry, so an
                # in-place status write would make a live->terminal
                # transition invisible (node usage keeps counting the
                # dead alloc). Send a copy carrying the new status.
                if status:
                    alloc = dc_replace(alloc, client_status=status)
                updates.append(alloc)
            if updates:
                srv.update_allocs_from_client(updates)
            self._respond({"Updated": len(updates)})
            return True

        m = re.fullmatch(r"/v1/node/([^/]+)/drain", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("node:write")
            body = self._body()
            enable = bool(
                body.get("DrainSpec") or body.get("drain", False)
            )
            strategy = None
            if enable:
                import time as _t

                spec = body.get("DrainSpec") or {}
                deadline_s = float(
                    spec.get("Deadline", 3600e9) / 1e9
                    if spec.get("Deadline")
                    else 3600.0
                )
                strategy = DrainStrategy(
                    ignore_system_jobs=bool(
                        spec.get("IgnoreSystemJobs", False)
                    ),
                    force_deadline_unix=_t.time() + deadline_s,
                )
            srv.update_node_drain(m.group(1), enable, strategy)
            self._respond({})
            return True

        m = re.fullmatch(r"/v1/node/([^/]+)/eligibility", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("node:write")
            body = self._body()
            elig = body.get("Eligibility") or body.get("eligibility")
            srv.update_node_eligibility(m.group(1), elig)
            self._respond({})
            return True

        if path == "/v1/allocations" and method == "GET":
            self._check_acl("read-job", ns)
            prefix = q.get("prefix", "")
            self._respond(
                [
                    alloc_to_dict(a)
                    for a in store.allocs.values()
                    if a.id.startswith(prefix)
                ]
            )
            return True

        m = re.fullmatch(r"/v1/allocation/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            alloc = store.alloc_by_id(m.group(1))
            if alloc is None:
                raise HTTPError(404, "alloc not found")
            self._respond(alloc_to_dict(alloc))
            return True

        m = re.fullmatch(r"/v1/allocation/([^/]+)/stop", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            try:
                ev = srv.stop_alloc(m.group(1))
            except KeyError:
                raise HTTPError(404, "alloc not found")
            self._respond({"EvalID": ev.id if ev else ""})
            return True

        m = re.fullmatch(
            r"/v1/client/allocation/([^/]+)/restart", path
        )
        if m and method in ("POST", "PUT"):
            self._check_acl("alloc-lifecycle", ns)
            body = self._body()
            try:
                srv.restart_alloc(
                    m.group(1), body.get("TaskName", "")
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond({})
            return True

        m = re.fullmatch(r"/v1/client/allocation/([^/]+)/exec", path)
        if (
            m
            and method == "GET"
            and "websocket"
            in self.headers.get("Upgrade", "").lower()
        ):
            # interactive exec over a websocket (reference
            # command/alloc_exec.go + api/allocations_exec.go frame
            # shapes: stdin/stdout/stderr data b64, tty_size, exited)
            self._check_acl("alloc-exec", ns)
            task = q.get("task", "")
            try:
                argv = json.loads(q.get("command", "[]"))
            except ValueError:
                raise HTTPError(400, "bad command encoding")
            if not argv:
                raise HTTPError(400, "missing command")
            try:
                handle = srv.exec_alloc_stream(
                    m.group(1), task, argv
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._serve_exec_websocket(handle)
            return True

        if m and method in ("POST", "PUT"):
            # one-shot exec in the task context (reference
            # command/alloc_exec.go; the reference streams over a
            # websocket, this returns the collected output)
            self._check_acl("alloc-exec", ns)
            body = self._body()
            argv = body.get("Cmd") or body.get("Command") or []
            if isinstance(argv, str):
                argv = [argv]
            if not argv:
                raise HTTPError(400, "missing command")
            try:
                code, output = srv.exec_alloc(
                    m.group(1),
                    body.get("Task", body.get("TaskName", "")),
                    argv,
                    timeout=float(body.get("Timeout", 30.0)),
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond(
                {
                    "ExitCode": code,
                    "Output": output.decode("utf-8", "replace"),
                }
            )
            return True

        m = re.fullmatch(r"/v1/client/fs/ls/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("read-fs", ns)
            try:
                self._respond(
                    srv.list_alloc_files(
                        m.group(1), q.get("path", "")
                    )
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            return True

        m = re.fullmatch(r"/v1/client/fs/cat/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("read-fs", ns)
            try:
                data, truncated = srv.read_alloc_file(
                    m.group(1), q.get("path", "")
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond(
                {
                    "Data": data.decode("utf-8", "replace"),
                    "Truncated": truncated,
                }
            )
            return True

        m = re.fullmatch(r"/v1/node/([^/]+)/purge", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("node:write")
            try:
                evals = srv.purge_node(m.group(1))
            except KeyError:
                raise HTTPError(404, "node not found")
            self._respond(
                {"EvalIDs": [e.id for e in evals]}
            )
            return True

        m = re.fullmatch(
            r"/v1/client/allocation/([^/]+)/signal", path
        )
        if m and method in ("POST", "PUT"):
            self._check_acl("alloc-lifecycle", ns)
            body = self._body()
            try:
                srv.signal_alloc(
                    m.group(1),
                    body.get("Signal", "SIGTERM"),
                    body.get("TaskName", body.get("Task", "")),
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            self._respond({})
            return True

        if path == "/v1/evaluations" and method == "GET":
            self._check_acl("read-job", ns)
            self._respond(
                [eval_to_dict(e) for e in store.evals.values()]
            )
            return True

        # placement explainability: the eval's retained per-TG score
        # decomposition + filter attribution from the explain ring
        # (cross-linked with /v1/traces/<eval_id>)
        m = re.fullmatch(r"/v1/evaluation/([^/]+)/placement", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            from ..explain import EXPLAIN

            record = EXPLAIN.get(m.group(1))
            if record is None and hasattr(srv, "cluster_query"):
                # follower-planned eval: the explain record lives on
                # whichever server ran the scheduler — fan the lookup
                # out so the operator never has to know which one
                merged = self._cluster_obs(
                    srv, "explain", {"eval_id": m.group(1)}
                )
                for addr, result in merged["servers"].items():
                    if result.get("unreachable"):
                        continue
                    found = result.get("explain")
                    if found is not None:
                        record = dict(found)
                        record["served_by"] = addr
                        break
            if record is None:
                raise HTTPError(404, "no placement explanation retained")
            self._respond(record)
            return True

        if path == "/v1/placements" and method == "GET":
            # recent placement explanations (newest first) — the
            # operator debug bundle's capture surface
            self._check_acl("read-job", ns)
            from ..explain import EXPLAIN

            try:
                limit = int(q.get("limit", "64"))
            except ValueError:
                raise HTTPError(400, "bad limit")
            self._respond(EXPLAIN.recent(limit=min(limit, 1024)))
            return True

        m = re.fullmatch(r"/v1/evaluation/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            ev = store.eval_by_id(m.group(1))
            if ev is None:
                raise HTTPError(404, "eval not found")
            payload = eval_to_dict(ev)
            if ev.failed_tg_allocs:
                # mirror the plan API's full Nomad shape (snake_case
                # struct fields stay for existing consumers)
                from ..explain import alloc_metric_to_api

                payload["FailedTGAllocs"] = {
                    tg: alloc_metric_to_api(metric)
                    for tg, metric in ev.failed_tg_allocs.items()
                }
            self._respond(payload)
            return True

        if path == "/v1/deployments" and method == "GET":
            self._check_acl("read-job", ns)
            self._respond(
                [deployment_to_dict(d) for d in store.deployments.values()]
            )
            return True

        m = re.fullmatch(r"/v1/deployment/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("read-job", ns)
            d = store.deployment_by_id(m.group(1))
            if d is None:
                raise HTTPError(404, "deployment not found")
            self._respond(deployment_to_dict(d))
            return True

        m = re.fullmatch(r"/v1/deployment/promote/([^/]+)", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            srv.deployment_watcher.promote(m.group(1))
            self._respond({})
            return True

        m = re.fullmatch(r"/v1/deployment/fail/([^/]+)", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            srv.deployment_watcher.fail(m.group(1))
            self._respond({})
            return True

        m = re.fullmatch(r"/v1/deployment/pause/([^/]+)", path)
        if m and method in ("POST", "PUT"):
            self._check_acl("submit-job", ns)
            body = self._body()
            srv.deployment_watcher.pause(
                m.group(1), bool(body.get("Pause", True))
            )
            self._respond({})
            return True

        # -- CSI volumes (reference command/agent/csi_endpoint.go) -----

        if path == "/v1/volumes" and method == "GET":
            self._check_acl("csi-list-volume", ns)
            vols = store.iter_csi_volumes(namespace=ns)
            self._respond([csi_volume_stub(v) for v in vols])
            return True

        m = re.fullmatch(r"/v1/volume/csi/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("csi-read-volume", ns)
            vol = store.csi_volume_by_id(ns, m.group(1))
            if vol is None:
                raise HTTPError(404, "volume not found")
            self._respond(csi_volume_to_dict(vol))
            return True

        if m and method in ("POST", "PUT"):
            self._check_acl("csi-write-volume", ns)
            body = self._body()
            batch = body.get("Volumes")
            for raw in batch or [body]:
                vol = csi_volume_from_dict(raw)
                if not vol.id:
                    if batch:
                        # the path id can only name ONE volume
                        raise HTTPError(
                            400, "volumes in a batch require an ID"
                        )
                    vol.id = m.group(1)
                if not vol.plugin_id:
                    raise HTTPError(400, "volume requires PluginID")
                vol.namespace = vol.namespace or ns
                store.upsert_csi_volume(vol)
            self._respond({})
            return True

        if m and method == "DELETE":
            self._check_acl("csi-write-volume", ns)
            try:
                store.deregister_csi_volume(
                    ns, m.group(1), force=q.get("force") == "true"
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond({})
            return True

        if path == "/v1/plugins" and method == "GET":
            self._check_acl("csi-list-volume", ns)
            self._respond(
                [
                    csi_plugin_to_dict(p)
                    for p in store.csi_plugins().values()
                ]
            )
            return True

        m = re.fullmatch(r"/v1/plugin/csi/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("csi-read-volume", ns)
            p = store.csi_plugins().get(m.group(1))
            if p is None:
                raise HTTPError(404, "plugin not found")
            self._respond(csi_plugin_to_dict(p))
            return True

        if path == "/v1/operator/scheduler/configuration":
            if method == "GET":
                cfg = store.get_scheduler_config()
                self._respond(
                    {
                        "SchedulerAlgorithm": cfg.scheduler_algorithm,
                        "TPUSchedulerEnabled": cfg.tpu_scheduler_enabled,
                        "PreemptionConfig": {
                            "SystemSchedulerEnabled": cfg.preemption_config.system_scheduler_enabled,
                            "BatchSchedulerEnabled": cfg.preemption_config.batch_scheduler_enabled,
                            "ServiceSchedulerEnabled": cfg.preemption_config.service_scheduler_enabled,
                        },
                    }
                )
                return True
            if method in ("POST", "PUT"):
                self._check_acl("operator:write")
                body = self._body()
                pre = body.get("PreemptionConfig", {})
                cfg = SchedulerConfiguration(
                    scheduler_algorithm=body.get(
                        "SchedulerAlgorithm", "binpack"
                    ),
                    tpu_scheduler_enabled=bool(
                        body.get("TPUSchedulerEnabled", False)
                    ),
                    preemption_config=PreemptionConfig(
                        system_scheduler_enabled=pre.get(
                            "SystemSchedulerEnabled", True
                        ),
                        batch_scheduler_enabled=pre.get(
                            "BatchSchedulerEnabled", False
                        ),
                        service_scheduler_enabled=pre.get(
                            "ServiceSchedulerEnabled", False
                        ),
                    ),
                )
                store.set_scheduler_config(cfg)
                self._respond({"Updated": True})
                return True

        if path == "/v1/catalog/services" and method == "GET":
            self._respond(srv.catalog.services())
            return True

        m = re.fullmatch(r"/v1/catalog/service/([^/]+)", path)
        if m and method == "GET":
            healthy = q.get("passing", "false") == "true"
            self._respond(
                [
                    {
                        "Service": i.service,
                        "AllocID": i.alloc_id,
                        "NodeID": i.node_id,
                        "Task": i.task,
                        "Address": i.address,
                        "Port": i.port,
                        "Tags": i.tags,
                        "Healthy": i.healthy,
                    }
                    for i in srv.catalog.instances(
                        m.group(1), healthy_only=healthy
                    )
                ]
            )
            return True

        if path == "/v1/status/leader" and method == "GET":
            raft = getattr(srv, "raft", None)
            self._respond(
                raft.leader_hint() if raft is not None else "local"
            )
            return True

        if path == "/v1/agent/join" and method in ("POST", "PUT"):
            # runtime cluster join (reference command/agent
            # /v1/agent/join -> srv.Join via serf)
            self._check_acl("agent:write")
            addr = q.get("address") or (self._body() or {}).get(
                "address", ""
            )
            if not addr:
                raise HTTPError(400, "missing address")
            join = getattr(srv, "join", None)
            if join is None:
                raise HTTPError(
                    400, "this agent is not a cluster server"
                )
            try:
                n = join(addr)
            except Exception as exc:  # noqa: BLE001
                raise HTTPError(500, f"join failed: {exc}")
            self._respond({"num_joined": int(n or 0)})
            return True

        if path == "/v1/agent/members" and method == "GET":
            gossip = getattr(srv, "gossip", None)
            self._respond(
                {
                    "ServerName": getattr(srv, "addr", "local"),
                    "ServerRegion": getattr(srv, "region", "global"),
                    "Members": gossip.member_list() if gossip else [
                        {"Name": "local", "Addr": "local",
                         "Status": "alive", "Region": "global",
                         "Role": "server", "Incarnation": 0}
                    ],
                }
            )
            return True

        if path == "/v1/agent/force-leave" and method in (
            "POST",
            "PUT",
        ):
            # evict a failed server from gossip (reference
            # agent_endpoint.go ForceLeave / `server force-leave`)
            self._check_acl("agent:write")
            name = q.get("node", "")
            if not name:
                raise HTTPError(400, "missing node")
            gossip = getattr(srv, "gossip", None)
            if gossip is None:
                raise HTTPError(
                    400, "agent is not running gossip"
                )
            gossip.force_leave(name)
            self._respond({})
            return True

        m = re.fullmatch(r"/v1/volume/csi/([^/]+)/detach", path)
        if m and method in ("POST", "PUT"):
            # release a node's claims on a volume (reference
            # csi_endpoint.go Unpublish / `volume detach`)
            self._check_acl("csi-write-volume", ns)
            node_id = q.get("node", "")
            if not node_id:
                raise HTTPError(400, "missing node")
            try:
                count = store.detach_csi_volume(
                    ns, m.group(1), node_id
                )
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            self._respond({"DetachedClaims": count})
            return True

        if path == "/v1/operator/raft/peer" and method == "DELETE":
            # remove a raft peer (reference operator_endpoint.go
            # RaftRemovePeerByAddress / `operator raft remove-peer`)
            # — through the REPLICATED config change so every server
            # agrees on the new membership, never the local-only
            # remove_peer
            self._check_acl("operator:write")
            address = q.get("address", "")
            if not address:
                raise HTTPError(400, "missing address")
            if hasattr(srv, "broadcast_peer_removal"):
                if not srv.broadcast_peer_removal(address):
                    raise HTTPError(
                        500, "peer removal not acknowledged"
                    )
            else:
                raft = getattr(srv, "raft", None)
                if raft is None or not hasattr(
                    raft, "remove_server"
                ):
                    raise HTTPError(
                        400, "server is not running raft"
                    )
                raft.remove_server(address)
            self._respond({})
            return True

        if path == "/v1/operator/license" and method == "GET":
            # OSS parity: the license surface exists but the feature
            # is Enterprise (reference OSS returns an error here)
            raise HTTPError(
                501, "license is a Nomad Enterprise feature"
            )
        if path == "/v1/operator/license" and method in (
            "POST",
            "PUT",
        ):
            raise HTTPError(
                501, "license is a Nomad Enterprise feature"
            )
        if path.startswith("/v1/sentinel") or path.startswith(
            "/v1/quota"
        ):
            # OSS parity (reference OSS: endpoints registered,
            # feature gated to Enterprise)
            raise HTTPError(
                501,
                "sentinel policies and quotas are Nomad "
                "Enterprise features",
            )

        if path == "/v1/operator/keyring" and method == "GET":
            self._check_acl("agent:read")
            self._respond(srv.keyring.list())
            return True
        if path == "/v1/operator/keyring" and method in (
            "POST",
            "PUT",
        ):
            self._check_acl("agent:write")
            body = self._body()
            op = body.get("Operation", "install")
            key = body.get("Key", "")
            try:
                if op == "install":
                    srv.keyring.install(key)
                elif op == "use":
                    srv.keyring.use(key)
                elif op == "remove":
                    srv.keyring.remove(key)
                else:
                    raise HTTPError(400, f"unknown op {op!r}")
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            self._respond(srv.keyring.list())
            return True

        if path == "/v1/regions" and method == "GET":
            gossip = getattr(srv, "gossip", None)
            if gossip is None:
                self._respond([getattr(srv, "region", "global")])
            else:
                self._respond(
                    sorted({m.region for m in gossip.alive_members()})
                )
            return True

        if path == "/v1/agent/self" and method == "GET":
            self._respond(
                {
                    "member": {"Name": "local", "Status": "alive"},
                    "stats": {
                        "broker": srv.broker.stats,
                        "blocked": srv.blocked.stats,
                        "plan_queue": srv.plan_queue.stats,
                    },
                }
            )
            return True

        if path == "/v1/agent/monitor" and method == "GET":
            self._check_acl("agent:read")
            if q.get("follow") == "true":
                # chunked live stream of agent log lines (reference
                # command/agent/monitor websocket stream); one JSON
                # line per log record
                def monitor_frames():
                    import time as _time

                    seq = int(q.get("index", "-1"))
                    deadline = _time.monotonic() + float(
                        q.get("max_s", "3600")
                    )
                    while _time.monotonic() < deadline:
                        lines, seq = srv.log_monitor.tail(
                            after=seq, wait=1.0
                        )
                        if not lines:
                            yield b""  # liveness probe tick
                        for line in lines:
                            yield (
                                json.dumps({"Line": line}) + "\n"
                            ).encode("utf-8")

                self._stream_chunked(
                    monitor_frames(), "application/json"
                )
                return True
            # log tail with a resumable cursor (reference
            # command/agent/monitor streaming; poll with ?index=<seq>)
            after = int(q.get("index", "-1"))
            wait_s = min(float(q.get("wait", "0")), 10.0)
            lines, seq = srv.log_monitor.tail(after=after, wait=wait_s)
            self._respond({"Lines": lines, "Index": seq})
            return True

        m = re.fullmatch(r"/v1/agent/pprof/([a-z]+)", path)
        if m and method == "GET":
            # python analogs of the go pprof profiles
            # (command/agent/http.go:303)
            self._check_acl("agent:read")
            from ..monitor import runtime_profile, thread_dump

            profile = m.group(1)
            if profile in ("goroutine", "threadcreate"):
                self._respond({"Profile": thread_dump()})
                return True
            if profile in ("heap", "allocs"):
                self._respond(runtime_profile())
                return True
            raise HTTPError(404, f"unknown profile {profile!r}")

        if path == "/v1/operator/autopilot/configuration":
            self._check_acl("operator:read")
            ap = getattr(srv, "autopilot", None)
            if ap is None:
                raise HTTPError(
                    404, "autopilot requires a clustered server"
                )
            if method == "GET":
                c = ap.config
                self._respond(
                    {
                        "CleanupDeadServers": c.cleanup_dead_servers,
                        "LastContactThreshold": (
                            c.last_contact_threshold_s
                        ),
                        "MaxTrailingLogs": c.max_trailing_logs,
                        "ServerStabilizationTime": (
                            c.server_stabilization_time_s
                        ),
                    }
                )
                return True
            if method in ("POST", "PUT"):
                # replicated write (raft), like scheduler config
                self._check_acl("operator:write")
                body = self._body()
                import dataclasses as _dc

                new_cfg = _dc.replace(ap.config)
                if "CleanupDeadServers" in body:
                    new_cfg.cleanup_dead_servers = bool(
                        body["CleanupDeadServers"]
                    )
                if "MaxTrailingLogs" in body:
                    new_cfg.max_trailing_logs = int(
                        body["MaxTrailingLogs"]
                    )
                if "LastContactThreshold" in body:
                    new_cfg.last_contact_threshold_s = float(
                        body["LastContactThreshold"]
                    )
                if "ServerStabilizationTime" in body:
                    new_cfg.server_stabilization_time_s = float(
                        body["ServerStabilizationTime"]
                    )
                store.set_autopilot_config(new_cfg)
                self._respond({"Updated": True})
                return True

        if path == "/v1/operator/autopilot/health" and method == "GET":
            self._check_acl("operator:read")
            ap = getattr(srv, "autopilot", None)
            if ap is None:
                raise HTTPError(
                    404, "autopilot requires a clustered server"
                )
            stats = ap.stats()
            self._respond(
                {
                    **stats,
                    "Servers": [
                        {
                            "ID": h.id,
                            "Name": h.name,
                            "Address": h.address,
                            "Healthy": h.healthy,
                            "Voter": h.voter,
                        }
                        for h in ap.server_health()
                    ],
                }
            )
            return True

        if path == "/v1/operator/raft/configuration" and method == "GET":
            self._check_acl("operator:read")
            raft = getattr(srv, "raft", None)
            if raft is None:
                # single-process server: itself is the whole config
                self._respond(
                    {"Servers": [], "Index": store.latest_index()}
                )
                return True
            leader_addr = (
                raft.addr if raft.is_leader() else raft.leader_hint()
            )
            self._respond(
                {
                    "Servers": [
                        {
                            "ID": addr,
                            "Address": addr,
                            "Leader": addr == leader_addr,
                            "Voter": True,
                        }
                        for addr in [raft.addr] + list(raft.peers)
                    ],
                    "Index": store.latest_index(),
                }
            )
            return True

        if path == "/v1/metrics" and method == "GET":
            metrics = getattr(srv, "metrics", None)
            if q.get("format") == "prometheus":
                # scrape format (reference /v1/metrics?format=prometheus)
                body = (
                    metrics.prometheus_text() if metrics else ""
                ).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return True
            self._respond(metrics.dump() if metrics else {})
            return True

        # metric time-series history: the retained snapshot windows
        # (NOMAD_TPU_OBS_HISTORY_N x NOMAD_TPU_OBS_HISTORY_S), or one
        # metric's series with ?name=.  Unauthenticated and never
        # shed, like /v1/metrics — it shares the prefix on purpose.
        if path == "/v1/metrics/history" and method == "GET":
            history = getattr(srv, "metrics_history", None)
            if history is None:
                self._respond({"enabled": False, "windows": []})
                return True
            name = q.get("name")
            if name:
                self._respond(
                    {"name": name, "series": history.series(name)}
                )
            else:
                self._respond(history.to_dict())
            return True

        # -- accelerator supervisor status ------------------------------
        # unauthenticated like /v1/metrics: this is the first endpoint
        # an operator polls when the device wedges, and it must answer
        # even when ACL state is part of what's broken
        if path == "/v1/device" and method == "GET":
            sup = getattr(srv, "device_supervisor", None)
            if sup is None:
                self._respond({"enabled": False, "state": "NONE"})
            else:
                self._respond(sup.status())
            return True

        # -- overload / degradation ladder ------------------------------
        # unauthenticated and NEVER shed, like /v1/metrics: the first
        # endpoint an operator (or a backing-off client) polls when
        # the server starts answering 429s
        if path == "/v1/overload" and method == "GET":
            ctl = getattr(srv, "overload", None)
            if ctl is None:
                self._respond({"enabled": False, "mode": 0})
            else:
                self._respond(ctl.status())
            return True

        # -- SLO burn-rate status --------------------------------------
        # unauthenticated and never shed, like /v1/overload: "are we
        # meeting our objectives" must answer exactly when we aren't
        if path == "/v1/slo" and method == "GET":
            slo = getattr(srv, "slo", None)
            if slo is None:
                self._respond(
                    {"enabled": False, "objectives": [], "worst": "OK"}
                )
            else:
                self._respond(slo.status())
            return True

        # -- adaptive-decision ledger ----------------------------------
        # agent:read like /v1/traces: decision inputs carry job ids,
        # node counts and backlog shapes across every namespace
        if path == "/v1/decisions" and method == "GET":
            self._check_acl("agent:read")
            from ..decisions import DECISIONS

            try:
                limit = int(q.get("limit", "64"))
            except ValueError:
                raise HTTPError(400, "bad limit")
            self._respond(
                DECISIONS.to_dict(
                    site=q.get("site"),
                    outcome=q.get("outcome"),
                    trace=q.get("trace"),
                    limit=max(1, min(limit, 1024)),
                )
            )
            return True

        # -- eval flight recorder (per-eval span traces) ----------------
        # agent:read like the other debug surfaces (monitor, pprof):
        # traces carry job ids and node ids across every namespace
        if path == "/v1/traces" and method == "GET":
            self._check_acl("agent:read")
            from ..trace import TRACE

            slow_ms = None
            if "slow_ms" in q:
                try:
                    slow_ms = float(q["slow_ms"])
                except ValueError:
                    raise HTTPError(400, "bad slow_ms")
            try:
                limit = int(q.get("limit", "64"))
            except ValueError:
                raise HTTPError(400, "bad limit")
            self._respond(
                TRACE.recent(
                    slow_ms=slow_ms,
                    outcome=q.get("outcome"),
                    limit=max(1, min(limit, 1024)),
                    full=q.get("full") == "1",
                )
            )
            return True

        m = re.fullmatch(r"/v1/traces/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("agent:read")
            from ..trace import TRACE

            trace = TRACE.get(m.group(1))
            if trace is None:
                raise HTTPError(404, "trace not found")
            self._respond(trace)
            return True

        # -- cluster-scope observability (leader fan-in) ----------------
        # the serving server fans the query out to every known peer
        # over the cluster transport (bounded by
        # NOMAD_TPU_OBS_FANIN_TIMEOUT_S); peers that time out are
        # marked unreachable in `servers`, never a failed query.  On a
        # single-process Server the same endpoints answer with the
        # local share only.
        if path == "/v1/cluster/traces" and method == "GET":
            self._check_acl("agent:read")
            params = {
                "limit": q.get("limit", "64"),
                "outcome": q.get("outcome"),
                "full": q.get("full") == "1",
            }
            if "slow_ms" in q:
                params["slow_ms"] = q["slow_ms"]
            merged = self._cluster_obs(
                srv, "traces", params, region=q.get("region")
            )
            traces = []
            status = {}
            seen = set()
            for addr, result in merged["servers"].items():
                if result.get("unreachable"):
                    status[addr] = "unreachable"
                    continue
                status[addr] = "ok"
                for entry in result.get("traces", []):
                    # dedup by trace id: with a shared in-process
                    # tracer (TestCluster) every server reports the
                    # same traces; first reporter wins the "server"
                    # attribution (local share is queried first)
                    tid = entry.get("trace_id") or entry.get("eval_id")
                    if tid in seen:
                        continue
                    seen.add(tid)
                    entry["server"] = addr
                    traces.append(entry)
            traces.sort(key=lambda t: t.get("start", 0), reverse=True)
            try:
                limit = int(params["limit"])
            except ValueError:
                raise HTTPError(400, "bad limit")
            self._respond(
                {
                    "traces": traces[: max(1, min(limit, 1024))],
                    "servers": status,
                    "unreachable": merged["unreachable"],
                }
            )
            return True

        m = re.fullmatch(r"/v1/cluster/traces/([^/]+)", path)
        if m and method == "GET":
            self._check_acl("agent:read")
            merged = self._cluster_obs(
                srv, "trace", {"ref": m.group(1)},
                region=q.get("region"),
            )
            best = None
            best_server = None
            status = {}
            for addr, result in merged["servers"].items():
                if result.get("unreachable"):
                    status[addr] = "unreachable"
                    continue
                status[addr] = "ok"
                trace = result.get("trace")
                if trace is None:
                    continue
                # the stitched whole lives on the server that rooted
                # the trace (the leader at dequeue time) — prefer the
                # most complete copy: finished beats in flight, more
                # spans beats fewer
                key = (
                    1 if trace.get("complete") else 0,
                    len(trace.get("spans") or ()),
                )
                if best is None or key > best_key:
                    best, best_key, best_server = trace, key, addr
            if best is None:
                raise HTTPError(404, "trace not found on any server")
            best["server"] = best_server
            best["servers"] = status
            self._respond(best)
            return True

        if path == "/v1/cluster/metrics" and method == "GET":
            self._check_acl("agent:read")
            merged = self._cluster_obs(
                srv, "metrics", {}, region=q.get("region")
            )
            servers = {
                addr: (
                    {"unreachable": True}
                    if result.get("unreachable")
                    else result.get("metrics", {})
                )
                for addr, result in merged["servers"].items()
            }
            self._respond(
                {
                    "servers": servers,
                    "unreachable": merged["unreachable"],
                }
            )
            return True

        if path == "/v1/cluster/metrics/history" and method == "GET":
            self._check_acl("agent:read")
            merged = self._cluster_obs(
                srv, "metrics_history", {}, region=q.get("region")
            )
            servers = {
                addr: (
                    {"unreachable": True}
                    if result.get("unreachable")
                    else result.get("history", {})
                )
                for addr, result in merged["servers"].items()
            }
            self._respond(
                {
                    "servers": servers,
                    "unreachable": merged["unreachable"],
                }
            )
            return True

        if path == "/v1/cluster/slo" and method == "GET":
            self._check_acl("agent:read")
            merged = self._cluster_obs(
                srv, "slo", {}, region=q.get("region")
            )
            servers = {
                addr: (
                    {"unreachable": True}
                    if result.get("unreachable")
                    else result.get("slo", {})
                )
                for addr, result in merged["servers"].items()
            }
            self._respond(
                {
                    "servers": servers,
                    "unreachable": merged["unreachable"],
                }
            )
            return True

        if path == "/v1/cluster/decisions" and method == "GET":
            self._check_acl("agent:read")
            params = {
                "limit": q.get("limit", "64"),
                "site": q.get("site"),
                "outcome": q.get("outcome"),
                "trace": q.get("trace"),
            }
            merged = self._cluster_obs(
                srv, "decisions", params, region=q.get("region")
            )
            decisions = []
            status = {}
            seen = set()
            for addr, result in merged["servers"].items():
                if result.get("unreachable"):
                    status[addr] = "unreachable"
                    continue
                status[addr] = "ok"
                share = result.get("decisions", {})
                for rec in share.get("decisions", []):
                    # dedup by ledger seq: with a shared in-process
                    # ledger (TestCluster) every server reports the
                    # same records; first reporter wins attribution
                    if rec.get("seq") in seen:
                        continue
                    seen.add(rec.get("seq"))
                    rec["server"] = addr
                    decisions.append(rec)
            decisions.sort(
                key=lambda r: r.get("seq", 0), reverse=True
            )
            try:
                limit = int(params["limit"])
            except ValueError:
                raise HTTPError(400, "bad limit")
            self._respond(
                {
                    "decisions": decisions[: max(1, min(limit, 1024))],
                    "servers": status,
                    "unreachable": merged["unreachable"],
                }
            )
            return True

        if path == "/v1/search" and method in ("POST", "PUT", "GET"):
            body = self._body() if method != "GET" else q
            prefix = body.get("Prefix") or body.get("prefix", "")
            context = body.get("Context") or body.get("context", "all")
            self._respond(self._search(store, prefix, context))
            return True

        # -- ACLs (reference nomad/acl_endpoint.go) ---------------------
        if path == "/v1/acl/bootstrap" and method in ("POST", "PUT"):
            acls = srv.acls
            if acls.tokens_by_secret:
                raise HTTPError(400, "ACL bootstrap already done")
            token = acls.bootstrap()
            self._respond(
                {
                    "AccessorID": token.accessor_id,
                    "SecretID": token.secret_id,
                    "Type": token.type,
                }
            )
            return True

        if path == "/v1/acl/policies" and method == "GET":
            self._check_acl("operator:read")
            self._respond(
                [
                    {"Name": p.name}
                    for p in srv.acls.policies.values()
                ]
            )
            return True

        m = re.fullmatch(r"/v1/acl/policy/([^/]+)", path)
        if m:
            from ..acl import Policy

            name = m.group(1)
            if method == "GET":
                self._check_acl("operator:read")
                policy = srv.acls.policies.get(name)
                if policy is None:
                    raise HTTPError(404, "policy not found")
                self._respond(
                    {
                        "Name": policy.name,
                        "Namespaces": {
                            ns: {
                                "Policy": np.policy,
                                "Capabilities": sorted(np.capabilities),
                            }
                            for ns, np in policy.namespaces.items()
                        },
                        "Node": policy.node,
                        "Operator": policy.operator,
                    }
                )
                return True
            if method in ("POST", "PUT"):
                self._check_acl("operator:write")
                body = self._body()
                rules = body.get("Rules") or body.get("rules") or body
                if isinstance(rules, str):
                    rules = json.loads(rules)
                srv.acls.upsert_policy(Policy.from_dict(name, rules))
                self._respond({})
                return True
            if method == "DELETE":
                self._check_acl("operator:write")
                srv.acls.delete_policy(name)
                self._respond({})
                return True

        if path == "/v1/acl/tokens":
            if method == "GET":
                self._check_acl("operator:read")
                self._respond(
                    [
                        {
                            "AccessorID": t.accessor_id,
                            "Name": t.name,
                            "Type": t.type,
                            "Policies": t.policies,
                        }
                        for t in srv.acls.tokens_by_accessor.values()
                    ]
                )
                return True
            if method in ("POST", "PUT"):
                self._check_acl("operator:write")
                from ..acl import Token

                body = self._body()
                token = Token(
                    name=body.get("Name", ""),
                    type=body.get("Type", "client"),
                    policies=body.get("Policies") or [],
                )
                srv.acls.create_token(token)
                self._respond(
                    {
                        "AccessorID": token.accessor_id,
                        "SecretID": token.secret_id,
                    }
                )
                return True

        if path == "/v1/acl/token/self" and method == "GET":
            token = srv.acls.tokens_by_secret.get(
                self.headers.get("X-Nomad-Token", "")
            )
            if token is None:
                raise HTTPError(403, "no token supplied or unknown")
            self._respond(
                {
                    "AccessorID": token.accessor_id,
                    "Name": token.name,
                    "Type": token.type,
                    "Policies": token.policies,
                }
            )
            return True

        m = re.fullmatch(r"/v1/acl/token/([^/]+)", path)
        if m and method == "DELETE":
            self._check_acl("operator:write")
            srv.acls.delete_token(m.group(1))
            self._respond({})
            return True
        if m and method == "GET":
            self._check_acl("operator:read")
            token = srv.acls.tokens_by_accessor.get(m.group(1))
            if token is None:
                raise HTTPError(404, "token not found")
            self._respond(
                {
                    "AccessorID": token.accessor_id,
                    "Name": token.name,
                    "Type": token.type,
                    "Policies": token.policies,
                }
            )
            return True
        if m and method in ("POST", "PUT"):
            self._check_acl("operator:write")
            token = srv.acls.tokens_by_accessor.get(m.group(1))
            if token is None:
                raise HTTPError(404, "token not found")
            body = self._body()
            import copy as _copy

            updated = _copy.copy(token)
            if "Name" in body:
                updated.name = body["Name"]
            if "Policies" in body:
                updated.policies = body["Policies"] or []
            if "Type" in body:
                updated.type = body["Type"]
            # create_token upserts by accessor/secret id and routes
            # through raft on replicated clusters
            try:
                srv.acls.create_token(updated)
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            self._respond({"AccessorID": updated.accessor_id})
            return True

        if path == "/v1/operator/snapshot/save" and method in ("POST", "PUT"):
            self._check_acl("operator:write")
            body = self._body()
            from ..server.snapshot import save_snapshot

            save_snapshot(srv, body["Path"])
            self._respond({"Saved": body["Path"]})
            return True

        if path == "/v1/operator/snapshot/restore" and method in ("POST", "PUT"):
            self._check_acl("operator:write")
            body = self._body()
            from ..server.snapshot import restore_snapshot

            index = restore_snapshot(srv, body["Path"])
            srv.restore_evals()
            self._respond({"Index": index})
            return True

        if path == "/v1/system/gc" and method in ("POST", "PUT"):
            self._check_acl("operator:write")
            srv.force_gc()
            self._respond({})
            return True

        if path == "/v1/system/reconcile/summaries" and method in (
            "POST",
            "PUT",
        ):
            # recompute every job's derived status/summary (reference
            # nomad/system_endpoint.go ReconcileJobSummaries); routes
            # through the store (raft on replicated clusters) so all
            # replicas converge and blocking queries wake
            self._check_acl("operator:write")
            store.reconcile_job_summaries()
            self._respond({})
            return True

        # -- namespaces (reference nomad/namespace_endpoint +
        # state table; OSS'd in 1.0) --------------------------------

        if path == "/v1/namespaces" and method == "GET":
            # filtered by the token's per-namespace capabilities
            # (reference namespace_endpoint.go ListNamespaces): a
            # token scoped to one namespace must not learn the
            # names/descriptions of the others; management sees all
            acls = getattr(srv, "acls", None)
            token_raw = self.headers.get("X-Nomad-Token", "")
            acl = (
                acls.resolve(token_raw)
                if acls is not None and acls.enabled
                else None
            )

            def ns_visible(name: str) -> bool:
                if acls is None or not acls.enabled:
                    return True
                if acl is None:
                    return False
                return any(
                    acl.allow_namespace_operation(name, c)
                    for c in ("read-job", "list-jobs")
                )

            visible = [
                n for n in store.iter_namespaces()
                if ns_visible(n.name)
            ]
            # A *resolved* token with zero visible namespaces gets [],
            # not 403 (reference ListNamespaces only denies anonymous/
            # invalid tokens) — narrowly-scoped automation must not see
            # an error where an empty list is the honest answer.
            if (
                acls is not None
                and acls.enabled
                and (not token_raw or acl is None)
            ):
                raise HTTPError(403, "Permission denied")
            self._respond(
                [
                    {
                        "Name": n.name,
                        "Description": n.description,
                        "CreateIndex": n.create_index,
                        "ModifyIndex": n.modify_index,
                    }
                    for n in visible
                ]
            )
            return True

        if path in ("/v1/namespaces", "/v1/namespace") and method in (
            "POST",
            "PUT",
        ):
            self._check_acl("operator:write")
            body = self._body()
            from ..structs import Namespace

            namespace = Namespace(
                name=body.get("Name", ""),
                description=body.get("Description", ""),
            )
            try:
                index = store.upsert_namespace(namespace)
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            self._respond({"Index": index})
            return True

        m = re.fullmatch(r"/v1/namespace/([^/]+)", path)
        if m and method == "GET":
            self._check_acl_any(("read-job", "list-jobs"), m.group(1))
            n = store.namespace_by_name(m.group(1))
            if n is None:
                raise HTTPError(404, "namespace not found")
            self._respond(
                {
                    "Name": n.name,
                    "Description": n.description,
                    "CreateIndex": n.create_index,
                    "ModifyIndex": n.modify_index,
                }
            )
            return True

        if m and method == "DELETE":
            self._check_acl("operator:write")
            try:
                index = store.delete_namespace(m.group(1))
            except KeyError as exc:
                raise HTTPError(404, str(exc))
            except ValueError as exc:
                raise HTTPError(400, str(exc))
            self._respond({"Index": index})
            return True

        return False

    @staticmethod
    def _search(store, prefix: str, context: str) -> Dict:
        """Prefix search over the main tables
        (reference nomad/search_endpoint.go)."""
        out: Dict[str, list] = {"Matches": {}, "Truncations": {}}
        limit = 20

        def matches(items):
            hits = [i for i in items if i.startswith(prefix)]
            return hits[:limit], len(hits) > limit

        if context in ("jobs", "all"):
            hits, trunc = matches([j.id for j in store.iter_jobs()])
            out["Matches"]["jobs"] = hits
            out["Truncations"]["jobs"] = trunc
        if context in ("nodes", "all"):
            hits, trunc = matches([n.id for n in store.iter_nodes()])
            out["Matches"]["nodes"] = hits
            out["Truncations"]["nodes"] = trunc
        if context in ("allocs", "all"):
            hits, trunc = matches(list(store.allocs))
            out["Matches"]["allocs"] = hits
            out["Truncations"]["allocs"] = trunc
        if context in ("evals", "all"):
            hits, trunc = matches(list(store.evals))
            out["Matches"]["evals"] = hits
            out["Truncations"]["evals"] = trunc
        if context in ("deployment", "all"):
            hits, trunc = matches(list(store.deployments))
            out["Matches"]["deployment"] = hits
            out["Truncations"]["deployment"] = trunc
        return out


class HTTPServer:
    def __init__(self, server, host: str = "127.0.0.1", port: int = 4646):
        handler = type("BoundHandler", (APIHandler,), {"server_ref": server})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-api", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def start_http_server(server, host="127.0.0.1", port=0) -> HTTPServer:
    http = HTTPServer(server, host, port)
    http.start()
    # gossip the bound HTTP address (cluster servers only): other
    # regions learn where to send redirected traffic — the shed
    # retry-region hint and the ?region= proxy both resolve through
    # these advertised addresses
    advertise = getattr(server, "advertise_http", None)
    if advertise is not None:
        advertise(f"{host}:{http.port}")
    return http
