"""JSON codecs for the API data model (reference api/ package types;
the reference's msgpack self-describing encoding maps to plain JSON
here)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ..structs import (
    Affinity,
    Allocation,
    Constraint,
    Deployment,
    EphemeralDisk,
    Evaluation,
    Job,
    MigrateStrategy,
    NetworkResource,
    Node,
    Periodic,
    Port,
    RequestedDevice,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    ScalingPolicy,
    UpdateStrategy,
    VolumeRequest,
)


def _clean(value: Any) -> Any:
    """Dataclass -> JSON-safe dict, dropping private/None-heavy noise."""
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {
            f.name: _clean(getattr(value, f.name))
            for f in dataclasses.fields(value)
            if f.name not in ("job", "metrics")  # avoid cycles/bloat
        }
    if isinstance(value, dict):
        return {str(k): _clean(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_clean(v) for v in value]
    if isinstance(value, bytes):
        # dispatch payloads ride the API base64-encoded, as in the
        # reference's api.Job Payload field
        import base64

        return base64.b64encode(value).decode()
    return value


def job_to_dict(job: Job) -> Dict:
    return _clean(job)


def node_to_dict(node: Node) -> Dict:
    return _clean(node)


def alloc_to_dict(alloc: Allocation) -> Dict:
    d = _clean(alloc)
    d["job_version"] = alloc.job.version if alloc.job else None
    return d


def dataclass_from_dict(cls, raw):
    """Generic inverse of _clean for the wire structs: rebuild a
    dataclass from its snake_case JSON form via type hints (List/
    Dict/Optional/nested dataclasses).  Unknown keys are ignored so
    additive wire fields never break older decoders; `job`/`metrics`
    never ride the wire (_clean drops them) and decode to their
    defaults."""
    import typing

    if raw is None or not dataclasses.is_dataclass(cls):
        return raw

    def thaw(hint, value):
        if value is None:
            return None
        origin = typing.get_origin(hint)
        if origin is typing.Union:
            args = [
                a
                for a in typing.get_args(hint)
                if a is not type(None)
            ]
            return thaw(args[0], value) if args else value
        if origin in (list, List):
            (item,) = typing.get_args(hint) or (Any,)
            return [thaw(item, v) for v in value]
        if origin in (dict, Dict):
            args = typing.get_args(hint) or (Any, Any)
            return {k: thaw(args[1], v) for k, v in value.items()}
        if dataclasses.is_dataclass(hint) and isinstance(value, dict):
            return dataclass_from_dict(hint, value)
        if hint is float:
            return float(value)
        if hint is int:
            return int(value)
        if hint is bool:
            return bool(value)
        if hint is bytes and isinstance(value, str):
            import base64

            return base64.b64decode(value)
        return value

    import typing as _t

    hints = _t.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in raw:
            kwargs[f.name] = thaw(hints[f.name], raw[f.name])
    return cls(**kwargs)


def _snake_keys(value):
    """Recursively normalize Go-style PascalCase keys to the structs'
    snake_case field names so dataclass_from_dict matches them
    (MemoryMB -> memory_mb, Vendor -> vendor).  snake_case keys pass
    through untouched."""
    import re as _re

    if isinstance(value, dict):
        out = {}
        for k, v in value.items():
            nk = k
            if isinstance(k, str) and k and k[0].isupper():
                nk = _re.sub(
                    r"(?<=[a-z0-9])(?=[A-Z])", "_", k
                ).lower()
            out[nk] = _snake_keys(v)
        return out
    if isinstance(value, list):
        return [_snake_keys(v) for v in value]
    return value


def alloc_from_dict(raw: Dict) -> Allocation:
    """Wire form -> Allocation (full decode incl. task_states and
    allocated_resources — what a remote client pushes and pulls;
    reference api/allocations.go shapes in snake_case)."""
    return dataclass_from_dict(Allocation, raw)


def eval_to_dict(ev: Evaluation) -> Dict:
    return _clean(ev)


def deployment_to_dict(d: Deployment) -> Dict:
    return _clean(d)


def csi_volume_to_dict(v) -> Dict:
    return {
        "ID": v.id,
        "Namespace": v.namespace,
        "Name": v.name,
        "ExternalID": v.external_id,
        "PluginID": v.plugin_id,
        "AccessMode": v.access_mode,
        "AttachmentMode": v.attachment_mode,
        "Schedulable": v.schedulable,
        "ReadAllocs": dict(v.read_claims),
        "WriteAllocs": dict(v.write_claims),
        "Parameters": dict(v.parameters),
        "Context": dict(v.context),
        "CreateIndex": v.create_index,
        "ModifyIndex": v.modify_index,
    }


def csi_volume_stub(v) -> Dict:
    return {
        "ID": v.id,
        "Namespace": v.namespace,
        "Name": v.name,
        "PluginID": v.plugin_id,
        "AccessMode": v.access_mode,
        "AttachmentMode": v.attachment_mode,
        "Schedulable": v.schedulable,
        "CurrentReaders": len(v.read_claims),
        "CurrentWriters": len(v.write_claims),
    }


def csi_volume_from_dict(raw: Dict):
    from ..structs import CSIVolume

    return CSIVolume(
        id=_get(raw, "id", "ID", default=""),
        # empty so callers can fall back to the request namespace
        namespace=_get(raw, "namespace", "Namespace", default=""),
        name=_get(raw, "name", "Name", default=""),
        external_id=_get(raw, "external_id", "ExternalID", default=""),
        plugin_id=_get(raw, "plugin_id", "PluginID", default=""),
        access_mode=_get(
            raw, "access_mode", "AccessMode",
            default="single-node-writer",
        ),
        attachment_mode=_get(
            raw, "attachment_mode", "AttachmentMode",
            default="file-system",
        ),
        schedulable=bool(
            _get(raw, "schedulable", "Schedulable", default=True)
        ),
        secrets=_get(raw, "secrets", "Secrets", default={}) or {},
        parameters=_get(raw, "parameters", "Parameters", default={}) or {},
        context=_get(raw, "context", "Context", default={}) or {},
    )


def csi_plugin_to_dict(p) -> Dict:
    return {
        "ID": p.id,
        "NodesHealthy": p.nodes_healthy,
        "NodesExpected": p.nodes_expected,
        "NodeIDs": list(p.node_ids),
    }


def scaling_policy_to_dict(p) -> Dict:
    return {
        "ID": p.id,
        "Type": p.type,
        "Target": dict(p.target),
        "Min": p.min,
        "Max": p.max,
        "Policy": dict(p.policy),
        "Enabled": p.enabled,
        "CreateIndex": p.create_index,
        "ModifyIndex": p.modify_index,
    }


def scaling_policy_stub(p) -> Dict:
    d = scaling_policy_to_dict(p)
    d.pop("Policy")
    return d


def scaling_event_to_dict(e) -> Dict:
    return {
        "Time": e.time,
        "Count": e.count,
        "PreviousCount": e.previous_count,
        "Message": e.message,
        "Error": e.error,
        "EvalID": e.eval_id,
        "Meta": dict(e.meta),
        "CreateIndex": e.create_index,
    }


# ---------------------------------------------------------------------------
# Job parsing from API dicts (accepts both snake_case and the reference
# API's CamelCase field names)
# ---------------------------------------------------------------------------


def _get(d: Dict, *names, default=None):
    for name in names:
        if name in d:
            return d[name]
    return default


def _constraints(raw) -> List[Constraint]:
    out = []
    for c in raw or []:
        out.append(
            Constraint(
                ltarget=_get(c, "ltarget", "LTarget", default=""),
                rtarget=_get(c, "rtarget", "RTarget", default=""),
                operand=_get(c, "operand", "Operand", default="="),
            )
        )
    return out


def _affinities(raw) -> List[Affinity]:
    out = []
    for a in raw or []:
        out.append(
            Affinity(
                ltarget=_get(a, "ltarget", "LTarget", default=""),
                rtarget=_get(a, "rtarget", "RTarget", default=""),
                operand=_get(a, "operand", "Operand", default="="),
                weight=int(_get(a, "weight", "Weight", default=50)),
            )
        )
    return out


def _spreads(raw) -> List[Spread]:
    out = []
    for s in raw or []:
        targets = tuple(
            SpreadTarget(
                value=_get(t, "value", "Value", default=""),
                percent=int(_get(t, "percent", "Percent", default=0)),
            )
            for t in _get(s, "targets", "SpreadTarget", default=[]) or []
        )
        out.append(
            Spread(
                attribute=_get(s, "attribute", "Attribute", default=""),
                weight=int(_get(s, "weight", "Weight", default=50)),
                targets=targets,
            )
        )
    return out


def _networks(raw) -> List[NetworkResource]:
    out = []
    for n in raw or []:
        reserved = [
            Port(
                label=_get(p, "label", "Label", default=""),
                value=int(_get(p, "value", "Value", "Static", default=0)),
                to=int(_get(p, "to", "To", default=0)),
            )
            for p in _get(n, "reserved_ports", "ReservedPorts", default=[])
            or []
        ]
        dynamic = [
            Port(
                label=_get(p, "label", "Label", default=""),
                to=int(_get(p, "to", "To", default=0)),
            )
            for p in _get(n, "dynamic_ports", "DynamicPorts", default=[])
            or []
        ]
        out.append(
            NetworkResource(
                mode=_get(n, "mode", "Mode", default="host"),
                mbits=int(_get(n, "mbits", "MBits", default=0)),
                reserved_ports=reserved,
                dynamic_ports=dynamic,
            )
        )
    return out


def _resources(raw) -> Resources:
    raw = raw or {}
    devices = []
    for dev in _get(raw, "devices", "Devices", default=[]) or []:
        devices.append(
            RequestedDevice(
                name=_get(dev, "name", "Name", default=""),
                count=int(_get(dev, "count", "Count", default=1)),
                constraints=_constraints(
                    _get(dev, "constraints", "Constraints")
                ),
                affinities=_affinities(
                    _get(dev, "affinities", "Affinities")
                ),
            )
        )
    return Resources(
        cpu=int(_get(raw, "cpu", "CPU", default=100)),
        memory_mb=int(_get(raw, "memory_mb", "MemoryMB", default=300)),
        disk_mb=int(_get(raw, "disk_mb", "DiskMB", default=0)),
        networks=_networks(_get(raw, "networks", "Networks")),
        devices=devices,
    )


def _service(raw):
    from ..structs import ConnectUpstream, ConsulConnect, Service

    connect = None
    cn = _get(raw, "connect", "Connect")
    if cn:
        connect = ConsulConnect(
            native=bool(_get(cn, "native", "Native", default=False)),
            sidecar_service=bool(
                _get(cn, "sidecar_service", "SidecarService",
                     default=False)
            ),
            upstreams=[
                ConnectUpstream(
                    destination_name=_get(
                        u, "destination_name", "DestinationName",
                        default="",
                    ),
                    local_bind_port=int(
                        _get(
                            u, "local_bind_port", "LocalBindPort",
                            default=0,
                        )
                    ),
                )
                for u in _get(cn, "upstreams", "Upstreams", default=[])
                or []
            ],
        )
    return Service(
        name=_get(raw, "name", "Name", default=""),
        port_label=str(
            _get(raw, "port_label", "PortLabel", "Port", default="")
        ),
        tags=_get(raw, "tags", "Tags", default=[]) or [],
        checks=_get(raw, "checks", "Checks", default=[]) or [],
        connect=connect,
    )


def _lifecycle(raw):
    from ..structs import Lifecycle

    if not raw:
        return None
    return Lifecycle(
        hook=_get(raw, "hook", "Hook", default=""),
        sidecar=bool(_get(raw, "sidecar", "Sidecar", default=False)),
    )


def _task(raw) -> Task:
    return Task(
        name=_get(raw, "name", "Name", default=""),
        driver=_get(raw, "driver", "Driver", default="exec"),
        config=_get(raw, "config", "Config", default={}) or {},
        env=_get(raw, "env", "Env", default={}) or {},
        resources=_resources(_get(raw, "resources", "Resources")),
        constraints=_constraints(_get(raw, "constraints", "Constraints")),
        affinities=_affinities(_get(raw, "affinities", "Affinities")),
        services=[
            _service(s)
            for s in _get(raw, "services", "Services", default=[]) or []
        ],
        lifecycle=_lifecycle(_get(raw, "lifecycle", "Lifecycle")),
        leader=bool(_get(raw, "leader", "Leader", default=False)),
        kill_timeout_s=float(
            _get(raw, "kill_timeout_s", "KillTimeout", default=5.0)
        ),
        meta=_get(raw, "meta", "Meta", default={}) or {},
    )


def _task_group(raw) -> TaskGroup:
    tg = TaskGroup(
        name=_get(raw, "name", "Name", default=""),
        count=int(_get(raw, "count", "Count", default=1)),
        tasks=[_task(t) for t in _get(raw, "tasks", "Tasks", default=[])],
        constraints=_constraints(_get(raw, "constraints", "Constraints")),
        affinities=_affinities(_get(raw, "affinities", "Affinities")),
        spreads=_spreads(_get(raw, "spreads", "Spreads")),
        networks=_networks(_get(raw, "networks", "Networks")),
        meta=_get(raw, "meta", "Meta", default={}) or {},
    )
    rp = _get(raw, "restart_policy", "RestartPolicy")
    if rp:
        tg.restart_policy = RestartPolicy(
            attempts=int(_get(rp, "attempts", "Attempts", default=2)),
            interval_s=float(_get(rp, "interval_s", "Interval", default=1800)),
            delay_s=float(_get(rp, "delay_s", "Delay", default=15)),
            mode=_get(rp, "mode", "Mode", default="fail"),
        )
    rsp = _get(raw, "reschedule_policy", "ReschedulePolicy")
    if rsp:
        tg.reschedule_policy = ReschedulePolicy(
            attempts=int(_get(rsp, "attempts", "Attempts", default=0)),
            interval_s=float(_get(rsp, "interval_s", "Interval", default=0)),
            delay_s=float(_get(rsp, "delay_s", "Delay", default=30)),
            delay_function=_get(
                rsp, "delay_function", "DelayFunction",
                default="exponential",
            ),
            max_delay_s=float(
                _get(rsp, "max_delay_s", "MaxDelay", default=3600)
            ),
            unlimited=bool(
                _get(rsp, "unlimited", "Unlimited", default=True)
            ),
        )
    upd = _get(raw, "update", "Update")
    if upd:
        tg.update = _update_strategy(upd)
    mig = _get(raw, "migrate", "Migrate")
    if mig:
        tg.migrate = MigrateStrategy(
            max_parallel=int(
                _get(mig, "max_parallel", "MaxParallel", default=1)
            ),
        )
    disk = _get(raw, "ephemeral_disk", "EphemeralDisk")
    if disk:
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(_get(disk, "sticky", "Sticky", default=False)),
            size_mb=int(_get(disk, "size_mb", "SizeMB", default=300)),
            migrate=bool(_get(disk, "migrate", "Migrate", default=False)),
        )
    vols = _get(raw, "volumes", "Volumes", default={}) or {}
    for name, v in vols.items():
        tg.volumes[name] = VolumeRequest(
            name=name,
            type=_get(v, "type", "Type", default="host"),
            source=_get(v, "source", "Source", default=""),
            read_only=bool(_get(v, "read_only", "ReadOnly", default=False)),
        )
    sc = _get(raw, "scaling", "Scaling")
    if sc:
        tg.scaling = ScalingPolicy(
            min=int(_get(sc, "min", "Min", default=1)),
            max=int(_get(sc, "max", "Max", default=0)),
            policy=_get(sc, "policy", "Policy", default={}) or {},
            enabled=bool(_get(sc, "enabled", "Enabled", default=True)),
        )
    return tg


def _update_strategy(raw) -> UpdateStrategy:
    return UpdateStrategy(
        stagger_s=float(_get(raw, "stagger_s", "Stagger", default=30)),
        max_parallel=int(
            _get(raw, "max_parallel", "MaxParallel", default=1)
        ),
        min_healthy_time_s=float(
            _get(raw, "min_healthy_time_s", "MinHealthyTime", default=10)
        ),
        healthy_deadline_s=float(
            _get(raw, "healthy_deadline_s", "HealthyDeadline", default=300)
        ),
        progress_deadline_s=float(
            _get(
                raw, "progress_deadline_s", "ProgressDeadline", default=600
            )
        ),
        auto_revert=bool(
            _get(raw, "auto_revert", "AutoRevert", default=False)
        ),
        auto_promote=bool(
            _get(raw, "auto_promote", "AutoPromote", default=False)
        ),
        canary=int(_get(raw, "canary", "Canary", default=0)),
    )


def node_from_dict(raw: Dict) -> "Node":
    """Inbound node registration payload -> Node (reference
    api/nodes.go shapes; accepts both snake_case and CamelCase)."""
    from ..structs import (
        Node,
        NodeReservedResources,
        NodeResources,
        compute_node_class,
    )

    res_raw = _get(raw, "node_resources", "NodeResources",
                   default={}) or {}
    reserved_raw = _get(
        raw, "reserved_resources", "ReservedResources", default={}
    ) or {}
    node = Node(
        id=_get(raw, "id", "ID", default=""),
        name=_get(raw, "name", "Name", default=""),
        datacenter=_get(
            raw, "datacenter", "Datacenter", default="dc1"
        ),
        node_class=_get(raw, "node_class", "NodeClass", default=""),
        attributes=_get(
            raw, "attributes", "Attributes", default={}
        ) or {},
        drivers={
            k: bool(v)
            for k, v in (
                _get(raw, "drivers", "Drivers", default={}) or {}
            ).items()
        },
        node_resources=NodeResources(
            cpu=int(_get(res_raw, "cpu", "Cpu", "CPU", default=0)),
            memory_mb=int(
                _get(res_raw, "memory_mb", "MemoryMB", default=0)
            ),
            disk_mb=int(
                _get(res_raw, "disk_mb", "DiskMB", default=0)
            ),
        ),
        reserved_resources=NodeReservedResources(
            cpu=int(
                _get(reserved_raw, "cpu", "Cpu", "CPU", default=0)
            ),
            memory_mb=int(
                _get(
                    reserved_raw, "memory_mb", "MemoryMB", default=0
                )
            ),
            disk_mb=int(
                _get(reserved_raw, "disk_mb", "DiskMB", default=0)
            ),
        ),
        status=_get(raw, "status", "Status", default="ready"),
    )
    devs = _get(res_raw, "devices", "Devices", default=None)
    if devs:
        from ..structs import NodeDeviceResource

        node.node_resources.devices = [
            dataclass_from_dict(NodeDeviceResource, _snake_keys(d))
            for d in devs
        ]
    nets = _get(res_raw, "networks", "Networks", default=None)
    if nets:
        node.node_resources.networks = [
            dataclass_from_dict(NetworkResource, _snake_keys(n))
            for n in nets
        ]
    node.computed_class = compute_node_class(node)
    return node


def job_from_dict(raw: Dict) -> Job:
    job = Job(
        id=_get(raw, "id", "ID", default=""),
        name=_get(raw, "name", "Name", default="")
        or _get(raw, "id", "ID", default=""),
        namespace=_get(raw, "namespace", "Namespace", default="default"),
        region=_get(raw, "region", "Region", default="global"),
        type=_get(raw, "type", "Type", default="service"),
        priority=int(_get(raw, "priority", "Priority", default=50)),
        datacenters=_get(
            raw, "datacenters", "Datacenters", default=["dc1"]
        ),
        task_groups=[
            _task_group(tg)
            for tg in _get(raw, "task_groups", "TaskGroups", default=[])
        ],
        constraints=_constraints(_get(raw, "constraints", "Constraints")),
        affinities=_affinities(_get(raw, "affinities", "Affinities")),
        spreads=_spreads(_get(raw, "spreads", "Spreads")),
        meta=_get(raw, "meta", "Meta", default={}) or {},
        all_at_once=bool(
            _get(raw, "all_at_once", "AllAtOnce", default=False)
        ),
    )
    upd = _get(raw, "update", "Update")
    if upd:
        job.update = _update_strategy(upd)
        for tg in job.task_groups:
            if tg.update is None:
                tg.update = job.update
    pol = _get(raw, "policy", "Policy")
    if pol:
        from ..structs import PolicySpec

        job.policy = PolicySpec(
            throughput={
                str(k): float(v)
                for k, v in (
                    _get(pol, "throughput", "Throughput", default={})
                    or {}
                ).items()
            },
            throughput_coefficient=float(
                _get(
                    pol,
                    "throughput_coefficient",
                    "ThroughputCoefficient",
                    default=1.0,
                )
            ),
            migration_coefficient=float(
                _get(
                    pol,
                    "migration_coefficient",
                    "MigrationCoefficient",
                    default=0.0,
                )
            ),
            min_runtime_s=float(
                _get(
                    pol, "min_runtime_s", "MinRuntimeS", default=0.0
                )
            ),
        )
    per = _get(raw, "periodic", "Periodic")
    if per:
        job.periodic = Periodic(
            enabled=bool(_get(per, "enabled", "Enabled", default=True)),
            spec=_get(per, "spec", "Spec", "Cron", default=""),
            prohibit_overlap=bool(
                _get(per, "prohibit_overlap", "ProhibitOverlap",
                     default=False)
            ),
        )
    mr = _get(raw, "multiregion", "Multiregion")
    if mr:
        from ..structs import (
            Multiregion,
            MultiregionRegion,
            MultiregionStrategy,
        )

        strat = _get(mr, "strategy", "Strategy") or {}
        job.multiregion = Multiregion(
            strategy=MultiregionStrategy(
                max_parallel=int(
                    _get(strat, "max_parallel", "MaxParallel", default=0)
                ),
                on_failure=_get(
                    strat, "on_failure", "OnFailure", default=""
                ),
            ),
            regions=[
                MultiregionRegion(
                    name=_get(r, "name", "Name", default=""),
                    count=int(_get(r, "count", "Count", default=0)),
                    datacenters=_get(
                        r, "datacenters", "Datacenters", default=[]
                    )
                    or [],
                    meta=_get(r, "meta", "Meta", default={}) or {},
                )
                for r in _get(mr, "regions", "Regions", default=[]) or []
            ],
        )
    param = _get(raw, "parameterized", "ParameterizedJob", "Parameterized")
    if param:
        job.parameterized = {
            "payload": _get(param, "payload", "Payload", default=""),
            "meta_required": _get(
                param, "meta_required", "MetaRequired", default=[]
            )
            or [],
            "meta_optional": _get(
                param, "meta_optional", "MetaOptional", default=[]
            )
            or [],
        }
    payload = _get(raw, "payload", "Payload")
    if payload:
        import base64

        job.payload = (
            base64.b64decode(payload)
            if isinstance(payload, str)
            else bytes(payload)
        )
    return job
