from .http import HTTPServer, start_http_server  # noqa: F401
from .codec import job_to_dict, job_from_dict  # noqa: F401
