"""Minimal WebSocket (RFC 6455) framing for the streaming endpoints.

The reference multiplexes raw yamux streams for `alloc exec`
(interactive stdin/stdout frames + terminal resize — reference
nomad/rpc.go handleStreamingConn, command/alloc_exec.go) and serves
them to the CLI over a websocket.  This build keeps the HTTP server as
the single transport: the exec endpoint upgrades the connection and
exchanges the same JSON frame shapes the reference API uses
({"stdin": {"data": b64}}, {"stdout": {"data": b64}},
{"tty_size": {...}}, {"exited": true, "result": {...}}).

Only the subset both our server and CLI need: no extensions, no
fragmentation of outgoing messages, text + binary + close/ping/pong
handling, client masking per spec.
"""
from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
from typing import Optional, Tuple

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1(
        (client_key + _GUID).encode("ascii")
    ).digest()
    return base64.b64encode(digest).decode("ascii")


def server_handshake(handler) -> bool:
    """Upgrade an http.server request to a websocket.  Returns True
    when the 101 was sent; the caller then owns handler.connection."""
    key = handler.headers.get("Sec-WebSocket-Key", "")
    if not key:
        return False
    handler.send_response(101, "Switching Protocols")
    handler.send_header("Upgrade", "websocket")
    handler.send_header("Connection", "Upgrade")
    handler.send_header("Sec-WebSocket-Accept", accept_key(key))
    handler.end_headers()
    handler.wfile.flush()
    return True


def _read_exact(sock_file, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock_file.read(n - len(buf))
        if not chunk:
            raise ConnectionError("websocket closed mid-frame")
        buf += chunk
    return buf


# one frame (or fragment train) may not exceed this — a client-
# supplied 2^63 length must not become a server-side allocation
MAX_FRAME_BYTES = 16 * 1024 * 1024


def read_frame(sock_file) -> Tuple[int, bytes]:
    """Returns (opcode, payload).  Handles masking and 16/64-bit
    lengths; coalesces continuation fragments."""
    opcode = None
    payload = b""
    while True:
        head = _read_exact(sock_file, 2)
        fin = head[0] & 0x80
        op = head[0] & 0x0F
        masked = head[1] & 0x80
        length = head[1] & 0x7F
        if length == 126:
            length = struct.unpack(
                ">H", _read_exact(sock_file, 2)
            )[0]
        elif length == 127:
            length = struct.unpack(
                ">Q", _read_exact(sock_file, 8)
            )[0]
        if length + len(payload) > MAX_FRAME_BYTES:
            raise ConnectionError(
                f"websocket frame too large ({length} bytes)"
            )
        mask = _read_exact(sock_file, 4) if masked else b""
        data = _read_exact(sock_file, length) if length else b""
        if mask:
            data = bytes(
                b ^ mask[i % 4] for i, b in enumerate(data)
            )
        if op != OP_CONT:
            opcode = op
        payload += data
        if fin:
            return opcode, payload


def write_frame(
    sock, opcode: int, payload: bytes, mask: bool = False
) -> None:
    head = bytes([0x80 | opcode])
    length = len(payload)
    mask_bit = 0x80 if mask else 0
    if length < 126:
        head += bytes([mask_bit | length])
    elif length < 65536:
        head += bytes([mask_bit | 126]) + struct.pack(">H", length)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", length)
    if mask:
        key = os.urandom(4)
        payload = bytes(
            b ^ key[i % 4] for i, b in enumerate(payload)
        )
        head += key
    sock.sendall(head + payload)


class WebSocketClient:
    """Tiny client for the CLI: connect, send/recv text frames."""

    def __init__(self, host: str, port: int, path: str,
                 headers: Optional[dict] = None) -> None:
        self.sock = socket.create_connection((host, port), timeout=30)
        key = base64.b64encode(os.urandom(16)).decode("ascii")
        lines = [
            f"GET {path} HTTP/1.1",
            f"Host: {host}:{port}",
            "Upgrade: websocket",
            "Connection: Upgrade",
            f"Sec-WebSocket-Key: {key}",
            "Sec-WebSocket-Version: 13",
        ]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        self.sock.sendall(
            ("\r\n".join(lines) + "\r\n\r\n").encode("ascii")
        )
        self._file = self.sock.makefile("rb")
        status = self._file.readline()
        if b"101" not in status:
            raise ConnectionError(
                f"websocket upgrade refused: {status!r}"
            )
        while True:
            line = self._file.readline()
            if line in (b"\r\n", b"\n", b""):
                break

    def send_text(self, text: str) -> None:
        write_frame(
            self.sock, OP_TEXT, text.encode("utf-8"), mask=True
        )

    def recv(self, timeout: Optional[float] = None):
        """Returns (opcode, payload) or None on clean close."""
        self.sock.settimeout(timeout)
        try:
            op, payload = read_frame(self._file)
        except (ConnectionError, OSError):
            return None
        if op == OP_CLOSE:
            return None
        if op == OP_PING:
            write_frame(self.sock, OP_PONG, payload, mask=True)
            return self.recv(timeout)
        return op, payload

    def close(self) -> None:
        try:
            write_frame(self.sock, OP_CLOSE, b"", mask=True)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
