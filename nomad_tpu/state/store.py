"""In-memory indexed state store.

Plays the role of the reference's go-memdb `StateStore`
(`nomad/state/state_store.go`, schema `nomad/state/schema.go:59`): tables
for nodes, jobs (+versions), allocs, evals, deployments, job summaries and
scheduler config, each with a modify-index, plus `upsert_plan_results`
(state_store.go:240), the single write path for scheduler plans.

Concurrency model (a deliberate departure from go-memdb's MVCC): the
control plane is a single-process event loop where plan application is
serialized (as in the reference, `nomad/plan_apply.go:45-70`), so a
"snapshot" is an O(1) fence — it records the current index and delegates
reads to the live tables; no mutation can interleave with a scheduler pass.
This keeps eval throughput free of O(cluster) snapshot copies, which
matters when the scoring backend is fast enough that snapshotting would
dominate.  `SnapshotAt` provides the same `snapshot_min_index` wait the
reference workers use (state_store.go:127).

The store also owns the columnar `NodeTable` mirror (the device-resident
"cluster tensor") and keeps it incrementally in sync on node/alloc writes.
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..trace import TRACE
from ..structs import (
    Allocation,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_DESIRED_STOP,
    CSIPlugin,
    CSIVolume,
    Deployment,
    Evaluation,
    Job,
    JOB_STATUS_DEAD,
    JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING,
    JOB_TYPE_SYSTEM,
    Namespace,
    Node,
    Plan,
    PlanResult,
    JOB_TRACKED_SCALING_EVENTS,
    ScalingEvent,
    ScalingPolicy,
    SchedulerConfiguration,
    compute_node_class,
)
from .node_table import NodeTable


class StateStore:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._index = 0
        self._table_index: Dict[str, int] = defaultdict(int)

        self.nodes: Dict[str, Node] = {}
        self.jobs: Dict[Tuple[str, str], Job] = {}
        self.job_versions: Dict[Tuple[str, str], List[Job]] = defaultdict(list)
        self.allocs: Dict[str, Allocation] = {}
        self.evals: Dict[str, Evaluation] = {}
        self.deployments: Dict[str, Deployment] = {}
        self.scheduler_config = SchedulerConfiguration()
        # autopilot operator config; None = compiled-in defaults
        self.autopilot_config = None

        # CSI volumes keyed (namespace, id) (reference state table
        # csi_volumes, nomad/state/schema.go)
        self.csi_volumes: Dict[Tuple[str, str], CSIVolume] = {}

        # namespaces (reference state table namespaces); "default"
        # always exists
        self.namespaces: Dict[str, "Namespace"] = {
            "default": Namespace(
                name="default", description="Default shared namespace"
            )
        }

        # autoscaling (reference state tables scaling_policy /
        # scaling_event, nomad/state/schema.go:795,847)
        self.scaling_policies: Dict[str, "ScalingPolicy"] = {}
        self._scaling_by_target: Dict[Tuple[str, str, str], str] = {}
        self.scaling_events: Dict[
            Tuple[str, str], Dict[str, List["ScalingEvent"]]
        ] = defaultdict(dict)

        # secondary indexes
        self._allocs_by_node: Dict[str, set] = defaultdict(set)
        self._allocs_by_job: Dict[Tuple[str, str], set] = defaultdict(set)
        self._allocs_by_eval: Dict[str, set] = defaultdict(set)
        self._evals_by_job: Dict[Tuple[str, str], set] = defaultdict(set)
        self._deployments_by_job: Dict[Tuple[str, str], set] = defaultdict(set)

        # columnar mirror of the node table + per-node live-usage columns
        self.node_table = NodeTable()
        # per-node mutation fingerprints: node_id -> count of writes
        # that touched that node's scheduling-relevant state (node
        # record writes AND each alloc write on the node).  The
        # BatchWorker's optimistic parallel replay uses them as its
        # conflict ledger: a speculative replay may only commit when
        # every node it read shows exactly the touch count it expects
        # (wave-start baseline plus the wave's own committed plans) —
        # any external write inflates the count and conflicts.  One
        # int per live node (entries are pruned on delete_node, so
        # node churn doesn't accumulate dead ids).
        self._node_touch: Dict[str, int] = {}
        # bumped only when the READY-node set can have changed (join,
        # leave, status/eligibility/drain flips) — the global conflict
        # fence for reads that scan all candidates (ready_nodes_in_dcs)
        self._readiness_gen = 0
        # live allocated static host ports: port -> {node_id: count},
        # plus the reverse map so per-node refresh never scans the
        # whole port dict
        self._ports_live: Dict[int, Dict[str, int]] = {}
        self._ports_by_node: Dict[str, set] = {}

        # bigworld allocation ballast: per-row (cpu, mem, disk) usage
        # seeded by bulk_seed_usage WITHOUT materializing Allocation
        # objects (10M allocs as dataclasses would cost tens of GB;
        # the array ledger is three f64 columns).  _live_usage_for_node
        # adds the row's ballast on every recompute so a real alloc
        # landing on a seeded node doesn't wipe the seeded base.
        self._seed_usage: Optional[List[np.ndarray]] = None
        self._seed_alloc_count = 0

        # change notification for blocking queries
        self._watch_cond = threading.Condition(self._lock)
        self._watchers: List[Callable[[str, int], None]] = []
        self._alloc_watchers: List[
            Callable[[List[Allocation]], None]
        ] = []
        # happens-before sanitizer (NOMAD_TPU_TSAN=1): inert one env
        # read otherwise
        from ..tsan import maybe_instrument

        maybe_instrument(self, "StateStore")

    # ------------------------------------------------------------------
    # index plumbing
    # ------------------------------------------------------------------

    def latest_index(self) -> int:
        return self._index

    def table_index(self, table: str) -> int:
        return self._table_index[table]

    def _bump(self, *tables: str) -> int:
        self._index += 1
        for t in tables:
            self._table_index[t] = self._index
        self._watch_cond.notify_all()
        for cb in self._watchers:
            for t in tables:
                cb(t, self._index)
        return self._index

    def add_watcher(self, cb: Callable[[str, int], None]) -> None:
        with self._lock:
            self._watchers.append(cb)

    def add_alloc_watcher(
        self, cb: Callable[[Optional[List[Allocation]]], None]
    ) -> None:
        """Delta-level watcher: called with exactly the allocations each
        write touched, so consumers (service catalog) can update
        incrementally instead of rescanning the whole alloc table.
        A ``None`` delta means the alloc table was replaced wholesale
        (snapshot restore) — consumers must resync from scratch."""
        with self._lock:
            self._alloc_watchers.append(cb)

    def wait_for_change(
        self, last_index: int, timeout: float = 1.0
    ) -> int:
        """Block until the store index advances past ``last_index`` or
        the timeout elapses; returns the current index.  This is the
        blocking-query primitive the leader-side watchers poll with
        (reference nomad/rpc.go:780 blockingRPC), replacing fixed-rate
        full-table sweeps."""
        deadline = time.monotonic() + timeout
        with self._watch_cond:
            while self._index <= last_index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._watch_cond.wait(remaining)
            return self._index

    def wait_for_index(self, index: int, timeout: float = 5.0) -> bool:
        """Block until the store has advanced to at least ``index``
        (reference state_store.go:127 SnapshotMinIndex)."""
        deadline = time.monotonic() + timeout
        with self._watch_cond:
            while self._index < index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._watch_cond.wait(remaining)
            return True

    def snapshot(self) -> "StateSnapshot":
        return StateSnapshot(self, self._index)

    def snapshot_min_index(self, index: int, timeout: float = 5.0) -> "StateSnapshot":
        if not self.wait_for_index(index, timeout):
            raise TimeoutError(
                f"timeout waiting for state at index {index} (at {self._index})"
            )
        return self.snapshot()

    # ------------------------------------------------------------------
    # nodes
    # ------------------------------------------------------------------

    def upsert_node(self, node: Node) -> int:
        with self._lock:
            if not node.computed_class:
                node.computed_class = compute_node_class(node)
            existing = self.nodes.get(node.id)
            if existing is not None:
                node.create_index = existing.create_index
            else:
                node.create_index = self._index + 1
            node.modify_index = self._index + 1
            was_ready = existing is not None and existing.ready()
            self.nodes[node.id] = node
            self.node_table.upsert_node(node)
            index = self._bump("nodes")
            self._touch_node(node.id)
            if existing is None or was_ready != node.ready():
                self._readiness_gen += 1
            # a changed node address must refresh the catalog entries of
            # allocs already running there (their instances captured the
            # old address when the alloc was last written)
            if (
                existing is not None
                and self._alloc_watchers
                and self._node_address(existing)
                != self._node_address(node)
            ):
                touched = [
                    self.allocs[aid]
                    for aid in self._allocs_by_node.get(node.id, ())
                    if aid in self.allocs
                ]
                self._notify_alloc_watchers(touched)
            return index

    @staticmethod
    def _node_address(node: Node) -> str:
        nets = node.node_resources.networks
        return nets[0].ip if nets else ""

    def bulk_register_nodes(self, nodes: List[Node]) -> int:
        """Register many FRESH synthetic nodes under ONE index bump —
        the bigworld seeding path.  Callers pre-set computed_class
        (the per-node class hash over a million template-sharing nodes
        is pure waste) and guarantee the ids are new.  Per-node touch
        counts are not seeded: an absent entry reads as 0, which is a
        valid conflict-ledger baseline."""
        if not nodes:
            return self._index
        with self._lock:
            idx = self._index + 1
            for node in nodes:
                node.create_index = idx
                node.modify_index = idx
                self.nodes[node.id] = node
            self.node_table.bulk_register_nodes(nodes)
            self._readiness_gen += 1
            return self._bump("nodes")

    def bulk_seed_usage(
        self,
        rows: np.ndarray,
        cpu: np.ndarray,
        mem: np.ndarray,
        disk: np.ndarray,
        alloc_count: int = 0,
    ) -> int:
        """Add allocation ballast to node rows as array columns — the
        usage the rows' live allocs WOULD exert if ``alloc_count``
        Allocation objects had been upserted, without materializing
        any of them.  Idempotent consumers see it as a normal usage
        delta (one generation, all touched rows dirty)."""
        with self._lock:
            cap = self.node_table.capacity
            if self._seed_usage is None or len(
                self._seed_usage[0]
            ) < cap:
                grown = [
                    np.zeros(cap, dtype=np.float64) for _ in range(3)
                ]
                if self._seed_usage is not None:
                    for g, o in zip(grown, self._seed_usage):
                        g[: len(o)] = o
                self._seed_usage = grown
            # this call's per-row aggregate (many allocs can land on
            # one row), folded into both the persistent ballast and
            # the live usage columns on top of whatever real allocs
            # already exert there
            agg = [np.zeros(cap, dtype=np.float64) for _ in range(3)]
            np.add.at(agg[0], rows, cpu)
            np.add.at(agg[1], rows, mem)
            np.add.at(agg[2], rows, disk)
            for base, a in zip(self._seed_usage, agg):
                base += a
            touched = np.unique(rows)
            table = self.node_table
            table.bulk_set_usage(
                touched,
                table.cpu_used[touched] + agg[0][touched],
                table.mem_used[touched] + agg[1][touched],
                table.disk_used[touched] + agg[2][touched],
            )
            self._seed_alloc_count += int(alloc_count)
            return self._bump("allocs")

    def seeded_alloc_count(self) -> int:
        """How many synthetic allocations back the ballast columns."""
        return self._seed_alloc_count

    def delete_node(self, node_id: str) -> int:
        with self._lock:
            if node_id in self.nodes:
                # a freed row can be reused by a future join; it must
                # not inherit this node's seeded allocation ballast
                if self._seed_usage is not None:
                    row = self.node_table.row_of.get(node_id)
                    if row is not None and row < len(
                        self._seed_usage[0]
                    ):
                        for base in self._seed_usage:
                            base[row] = 0.0
                del self.nodes[node_id]
                self.node_table.delete_node(node_id)
                self._readiness_gen += 1
                # prune the conflict-ledger entry so churned node ids
                # don't accumulate forever; the readiness bump above
                # already conflicts any in-flight replay wave, so the
                # count reset can't mask a mid-wave delete+re-register
                self._node_touch.pop(node_id, None)
            return self._bump("nodes")

    def update_node_status(
        self, node_id: str, status: str, now: Optional[float] = None
    ) -> int:
        # `now` is stamped by the proposer so a replicated command
        # stream applies identically on every server (FSM determinism)
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(node_id)
            was_ready = node.ready()
            node.status = status
            node.status_updated_at = time.time() if now is None else now
            node.modify_index = self._index + 1
            self.node_table.upsert_node(node)
            index = self._bump("nodes")
            self._touch_node(node_id)
            if was_ready != node.ready():
                self._readiness_gen += 1
            return index

    def update_node_statuses(
        self,
        node_ids,
        status: str,
        now: Optional[float] = None,
        message: str = "",
    ) -> int:
        """One batched status transition for a whole wave of nodes —
        the mass node-death path.  ONE lock acquisition and ONE index
        bump cover every member (a 500-node rack death is one FSM
        apply, not 500 serialized writes under the lock), and the
        optional ``message`` lands as one NodeEvent per member inside
        the same critical section.  Unknown node ids are skipped (a
        purge racing the sweep must not fail the wave).  ``now`` is
        stamped by the proposer (FSM determinism, like
        update_node_status)."""
        from ..structs import NodeEvent

        stamp = time.time() if now is None else now
        with self._lock:
            readiness_flips = 0
            touched = False
            for node_id in node_ids:
                node = self.nodes.get(node_id)
                if node is None:
                    continue
                touched = True
                was_ready = node.ready()
                node.status = status
                node.status_updated_at = stamp
                node.modify_index = self._index + 1
                self.node_table.upsert_node(node)
                self._touch_node(node_id)
                if was_ready != node.ready():
                    readiness_flips += 1
                if message:
                    ev = NodeEvent(
                        message=message, subsystem="Cluster"
                    )
                    ev.create_index = self._index + 1
                    node.add_event(ev)
            if readiness_flips:
                self._readiness_gen += 1
            if not touched:
                return self._index
            return self._bump("nodes")

    def update_node_eligibility(self, node_id: str, eligibility: str) -> int:
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(node_id)
            was_ready = node.ready()
            node.scheduling_eligibility = eligibility
            node.modify_index = self._index + 1
            self.node_table.upsert_node(node)
            index = self._bump("nodes")
            self._touch_node(node_id)
            if was_ready != node.ready():
                self._readiness_gen += 1
            return index

    def update_node_drain(
        self, node_id: str, drain: bool, strategy=None
    ) -> int:
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(node_id)
            node.drain = drain
            node.drain_strategy = strategy
            from ..structs import NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE

            node.scheduling_eligibility = (
                NODE_SCHED_INELIGIBLE if drain else NODE_SCHED_ELIGIBLE
            )
            node.modify_index = self._index + 1
            self.node_table.upsert_node(node)
            index = self._bump("nodes")
            self._touch_node(node_id)
            self._readiness_gen += 1
            return index

    def upsert_node_events(self, node_id: str, events) -> int:
        """Append to a node's bounded event history (reference
        state_store.go UpsertNodeEvents, fsm.go:247
        UpsertNodeEventsType)."""
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                raise KeyError(node_id)
            for ev in events:
                ev.create_index = self._index + 1
                node.add_event(ev)
            node.modify_index = self._index + 1
            return self._bump("nodes")

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self.nodes.get(node_id)

    def iter_nodes(self) -> Iterable[Node]:
        return list(self.nodes.values())

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------

    def upsert_job(self, job: Job, keep_versions: int = 6) -> int:
        with self._lock:
            key = (job.namespace, job.id)
            existing = self.jobs.get(key)
            if existing is not None:
                job.create_index = existing.create_index
                job.version = existing.version + 1
            else:
                job.create_index = self._index + 1
                job.version = 0
            job.modify_index = self._index + 1
            job.job_modify_index = self._index + 1
            if job.status not in (JOB_STATUS_DEAD,):
                job.status = JOB_STATUS_PENDING
            self.jobs[key] = job
            versions = self.job_versions[key]
            versions.insert(0, job)
            del versions[keep_versions:]
            self._sync_scaling_policies(job)
            return self._bump("jobs")

    def delete_job(self, namespace: str, job_id: str) -> int:
        with self._lock:
            key = (namespace, job_id)
            self.jobs.pop(key, None)
            self.job_versions.pop(key, None)
            self._drop_scaling_policies(namespace, job_id)
            self.scaling_events.pop(key, None)
            return self._bump("jobs")

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self.jobs.get((namespace, job_id))

    def job_by_version(
        self, namespace: str, job_id: str, version: int
    ) -> Optional[Job]:
        for j in self.job_versions.get((namespace, job_id), []):
            if j.version == version:
                return j
        return None

    def versions_of_job(
        self, namespace: str, job_id: str
    ) -> List[Job]:
        """All retained versions, newest first (reference
        state_store.go JobVersionsByID)."""
        return list(self.job_versions.get((namespace, job_id), []))

    def set_job_stability(
        self, namespace: str, job_id: str, version: int, stable: bool
    ) -> int:
        """(reference state_store.go UpdateJobStability)"""
        with self._lock:
            job = self.job_by_version(namespace, job_id, version)
            if job is None:
                raise KeyError(f"job {job_id!r} version {version}")
            job.stable = stable
            return self._bump("jobs")

    def iter_jobs(self) -> Iterable[Job]:
        return list(self.jobs.values())

    # ------------------------------------------------------------------
    # scaling policies + events (reference state_store.go
    # UpsertScalingPolicies / UpsertScalingEvent; policies live/die with
    # their job, nomad/state/state_store.go job upsert path)
    # ------------------------------------------------------------------

    def _sync_scaling_policies(self, job: Job) -> None:
        """Derive scaling policies from the job's task-group scaling
        stanzas.  Policy ids are stable across job versions: an update
        to a group keeps the policy id keyed by (ns, job, group)."""
        live_targets = set()
        for tg in job.task_groups:
            pol = getattr(tg, "scaling", None)
            if pol is None:
                continue
            pol.canonicalize_for(job, tg.name)
            target = pol.target_tuple()
            live_targets.add(target)
            existing_id = self._scaling_by_target.get(target)
            if existing_id is not None:
                pol.id = existing_id
                pol.create_index = self.scaling_policies[
                    existing_id
                ].create_index
            else:
                pol.create_index = self._index + 1
            pol.modify_index = self._index + 1
            self.scaling_policies[pol.id] = pol
            self._scaling_by_target[target] = pol.id
        # drop policies for groups removed from the job
        for target, pid in list(self._scaling_by_target.items()):
            ns, jid, _group = target
            if (ns, jid) == (job.namespace, job.id) and (
                target not in live_targets
            ):
                del self._scaling_by_target[target]
                self.scaling_policies.pop(pid, None)

    def _drop_scaling_policies(self, namespace: str, job_id: str) -> None:
        for target, pid in list(self._scaling_by_target.items()):
            if (target[0], target[1]) == (namespace, job_id):
                del self._scaling_by_target[target]
                self.scaling_policies.pop(pid, None)

    def scaling_policy_by_id(self, policy_id: str) -> Optional[ScalingPolicy]:
        return self.scaling_policies.get(policy_id)

    def scaling_policy_by_target(
        self, namespace: str, job_id: str, group: str
    ) -> Optional[ScalingPolicy]:
        pid = self._scaling_by_target.get((namespace, job_id, group))
        return self.scaling_policies.get(pid) if pid else None

    def iter_scaling_policies(
        self, namespace: Optional[str] = None, job_id: Optional[str] = None
    ) -> List[ScalingPolicy]:
        out = []
        for pol in self.scaling_policies.values():
            ns, jid, _ = pol.target_tuple()
            if namespace is not None and ns != namespace:
                continue
            if job_id is not None and jid != job_id:
                continue
            out.append(pol)
        return out

    def upsert_scaling_event(
        self, namespace: str, job_id: str, group: str, event: ScalingEvent
    ) -> int:
        with self._lock:
            event.create_index = self._index + 1
            events = self.scaling_events[(namespace, job_id)].setdefault(
                group, []
            )
            events.insert(0, event)
            del events[JOB_TRACKED_SCALING_EVENTS:]
            return self._bump("scaling_event")

    def scaling_events_for_job(
        self, namespace: str, job_id: str
    ) -> Dict[str, List[ScalingEvent]]:
        return {
            g: list(evs)
            for g, evs in self.scaling_events.get(
                (namespace, job_id), {}
            ).items()
        }

    # ------------------------------------------------------------------
    # CSI volumes (reference state_store.go CSIVolumeRegister/
    # CSIVolumeClaim/CSIVolumeDeregister; plugin health is a derived
    # view over node fingerprints)
    # ------------------------------------------------------------------

    # ------------------------------------------------------------------
    # namespaces (reference state_store.go UpsertNamespaces/
    # DeleteNamespaces; table nomad/state/schema.go)
    # ------------------------------------------------------------------

    def upsert_namespace(self, ns: Namespace) -> int:
        ns.validate()
        with self._lock:
            existing = self.namespaces.get(ns.name)
            if existing is None:
                ns.create_index = self._index + 1
            else:
                ns.create_index = existing.create_index
            ns.modify_index = self._index + 1
            self.namespaces[ns.name] = ns
            return self._bump("namespaces")

    def delete_namespace(self, name: str) -> int:
        with self._lock:
            if name == "default":
                raise ValueError(
                    "default namespace can not be deleted"
                )
            if name not in self.namespaces:
                raise KeyError(f"namespace {name!r} does not exist")
            # non-empty namespaces refuse deletion (reference
            # nomad/state namespace deletion checks jobs + volumes)
            jobs = [j for (n, _), j in self.jobs.items() if n == name]
            vols = [
                v for (n, _), v in self.csi_volumes.items() if n == name
            ]
            if jobs or vols:
                raise ValueError(
                    f"namespace {name!r} has {len(jobs)} jobs and "
                    f"{len(vols)} volumes; delete them first"
                )
            del self.namespaces[name]
            return self._bump("namespaces")

    def reconcile_job_summaries(self) -> int:
        """Recompute every job's derived status under the lock
        (reference nomad/system_endpoint.go ReconcileJobSummaries →
        raft ReconcileJobSummariesRequestType); bumps the jobs index so
        blocking queries wake."""
        with self._lock:
            for (ns, job_id), job in self.jobs.items():
                job.status = self.derive_job_status(ns, job_id)
            return self._bump("jobs")

    def namespace_by_name(self, name: str) -> Optional[Namespace]:
        return self.namespaces.get(name)

    def iter_namespaces(self) -> List[Namespace]:
        with self._lock:
            return sorted(
                self.namespaces.values(), key=lambda n: n.name
            )

    def upsert_csi_volume(self, volume: CSIVolume) -> int:
        with self._lock:
            key = (volume.namespace, volume.id)
            existing = self.csi_volumes.get(key)
            if existing is not None:
                volume.create_index = existing.create_index
                # claims survive a re-register (reference: volume
                # updates cannot drop live claims)
                volume.read_claims = dict(existing.read_claims)
                volume.write_claims = dict(existing.write_claims)
            else:
                volume.create_index = self._index + 1
            volume.modify_index = self._index + 1
            self.csi_volumes[key] = volume
            return self._bump("csi_volumes")

    def deregister_csi_volume(
        self, namespace: str, volume_id: str, force: bool = False
    ) -> int:
        with self._lock:
            vol = self.csi_volumes.get((namespace, volume_id))
            if vol is None:
                raise KeyError(f"volume {volume_id!r} not found")
            if vol.in_use() and not force:
                raise ValueError(
                    f"volume {volume_id!r} has active claims"
                )
            del self.csi_volumes[(namespace, volume_id)]
            return self._bump("csi_volumes")

    def csi_volume_by_id(
        self, namespace: str, volume_id: str
    ) -> Optional[CSIVolume]:
        return self.csi_volumes.get((namespace, volume_id))

    def iter_csi_volumes(
        self, namespace: Optional[str] = None
    ) -> List[CSIVolume]:
        return [
            v
            for v in self.csi_volumes.values()
            if namespace is None or v.namespace == namespace
        ]

    def claim_csi_volume(
        self,
        namespace: str,
        volume_id: str,
        alloc_id: str,
        node_id: str,
        read_only: bool,
    ) -> int:
        with self._lock:
            vol = self.csi_volumes.get((namespace, volume_id))
            if vol is None:
                raise KeyError(f"volume {volume_id!r} not found")
            if alloc_id not in vol.read_claims and (
                alloc_id not in vol.write_claims
            ):
                if not vol.claimable(read_only):
                    raise ValueError(
                        f"volume {volume_id!r} is not claimable "
                        f"({vol.access_mode})"
                    )
                vol.claim(alloc_id, node_id, read_only)
            vol.modify_index = self._index + 1
            return self._bump("csi_volumes")

    def detach_csi_volume(
        self, namespace: str, volume_id: str, node_id: str
    ) -> int:
        """Drop every claim a node holds on one volume (reference
        csi_endpoint.go Unpublish backing `volume detach`).  Returns
        the number of claims released."""
        with self._lock:
            vol = self.csi_volumes.get((namespace, volume_id))
            if vol is None:
                raise KeyError(f"volume {volume_id!r} not found")
            released = 0
            for claims in (vol.read_claims, vol.write_claims):
                for alloc_id, claim_node in list(claims.items()):
                    if claim_node == node_id:
                        del claims[alloc_id]
                        released += 1
            if released:
                vol.modify_index = self._index + 1
                self._bump("csi_volumes")
            return released

    def release_csi_claims_for_alloc(self, alloc_id: str) -> Optional[int]:
        """Drop every claim held by one alloc (the volume watcher's
        write path, reference volumewatcher/volumes_watcher.go)."""
        with self._lock:
            hit = False
            for vol in self.csi_volumes.values():
                if vol.release(alloc_id):
                    vol.modify_index = self._index + 1
                    hit = True
            if not hit:
                return None
            return self._bump("csi_volumes")

    def csi_plugins(self) -> Dict[str, CSIPlugin]:
        """Aggregate per-plugin health from node fingerprints."""
        with self._lock:
            plugins: Dict[str, CSIPlugin] = {}
            for node in self.nodes.values():
                for pid, healthy in node.csi_node_plugins.items():
                    p = plugins.setdefault(pid, CSIPlugin(id=pid))
                    p.nodes_expected += 1
                    if healthy:
                        p.nodes_healthy += 1
                        p.node_ids.append(node.id)
            return plugins

    # ------------------------------------------------------------------
    # evals
    # ------------------------------------------------------------------

    def upsert_evals(
        self, evals: List[Evaluation], now: Optional[float] = None
    ) -> int:
        if now is None:
            now = time.time()
        with self._lock:
            for ev in evals:
                existing = self.evals.get(ev.id)
                if existing is not None:
                    ev.create_index = existing.create_index
                else:
                    ev.create_index = self._index + 1
                ev.modify_index = self._index + 1
                ev.modify_time = now
                self.evals[ev.id] = ev
                self._evals_by_job[(ev.namespace, ev.job_id)].add(ev.id)
            return self._bump("evals")

    def delete_eval(self, eval_id: str) -> None:
        with self._lock:
            ev = self.evals.pop(eval_id, None)
            if ev is not None:
                self._evals_by_job[(ev.namespace, ev.job_id)].discard(eval_id)
            self._bump("evals")

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self.evals.get(eval_id)

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        return [
            self.evals[eid]
            for eid in self._evals_by_job.get((namespace, job_id), ())
            if eid in self.evals
        ]

    # ------------------------------------------------------------------
    # allocs
    # ------------------------------------------------------------------

    def upsert_allocs(self, allocs: List[Allocation]) -> int:
        with self._lock:
            self._upsert_allocs_locked(allocs)
            index = self._bump("allocs")
            self._notify_alloc_watchers(allocs)
            return index

    def _notify_alloc_watchers(self, allocs: List[Allocation]) -> None:
        """Called under self._lock so concurrent writers deliver deltas
        in commit order (out-of-order delivery would let a stale live
        version of an alloc overwrite its terminal update in the
        catalog).  Callbacks must only use the store's lock-free read
        surface.  ``allocs=None`` signals a wholesale table replacement
        (snapshot restore)."""
        if allocs or allocs is None:
            for cb in self._alloc_watchers:
                cb(allocs)

    def _upsert_allocs_locked(self, allocs: List[Allocation]) -> None:
        for alloc in allocs:
            existing = self.allocs.get(alloc.id)
            if existing is not None:
                alloc.create_index = existing.create_index
                # preserve the job from the existing alloc if absent
                if alloc.job is None:
                    alloc.job = existing.job
                was_live = not existing.terminal_status()
            else:
                alloc.create_index = self._index + 1
                was_live = False
            alloc.modify_index = self._index + 1
            self.allocs[alloc.id] = alloc
            # conflict ledger: any alloc write mutates its node's
            # schedulable state (usage, ports, devices, proposed set)
            self._touch_node(alloc.node_id)
            self._allocs_by_node[alloc.node_id].add(alloc.id)
            self._allocs_by_job[(alloc.namespace, alloc.job_id)].add(alloc.id)
            if alloc.eval_id:
                self._allocs_by_eval[alloc.eval_id].add(alloc.id)
            is_live = not alloc.terminal_status()
            # existing is alloc: an aliasing caller mutated the stored
            # object in place, so was_live is unknowable — recompute
            # usage unconditionally rather than miss a live->terminal
            if was_live != is_live or existing is None or existing is alloc:
                self.node_table.update_node_usage(
                    alloc.node_id, self._live_usage_for_node(alloc.node_id)
                )
            # port occupancy follows the same lifecycle, but also
            # shifts when an update re-offers ports on the same node
            self._refresh_port_index(alloc.node_id)

    def _live_usage_for_node(self, node_id: str):
        cpu = mem = disk = 0
        if self._seed_usage is not None:
            row = self.node_table.row_of.get(node_id)
            if row is not None and row < len(self._seed_usage[0]):
                cpu = int(self._seed_usage[0][row])
                mem = int(self._seed_usage[1][row])
                disk = int(self._seed_usage[2][row])
        for aid in self._allocs_by_node.get(node_id, ()):
            a = self.allocs[aid]
            if a.terminal_status():
                continue
            c = a.comparable_resources()
            cpu += c.cpu
            mem += c.memory_mb
            disk += c.disk_mb
        return cpu, mem, disk

    def _refresh_port_index(self, node_id: str) -> None:
        """Per-node recount of live allocated static host ports, from
        both group-level offers (shared.ports) and task-level network
        offers (tasks[*].networks — rank.py assign_network stores them
        there, never in shared.ports).  Keyed port -> {node_id: count}
        so the batch prescorer can build per-port occupancy columns
        without scanning the whole alloc set (reference builds a
        NetworkIndex per candidate node lazily — rank.go network
        path; the kernel needs all nodes up front).  Dynamic-range
        ports are skipped: static asks in that range are gated to the
        sequential path, so the index is never queried for them."""
        from ..structs.network import MIN_DYNAMIC_PORT

        for port in self._ports_by_node.pop(node_id, ()):
            nodes = self._ports_live.get(port)
            if nodes is not None:
                nodes.pop(node_id, None)
                if not nodes:
                    del self._ports_live[port]
        # device reservations live in ONE index — the node table's
        # device_used, read by the per-select mask (MaskCompiler.
        # device_feasibility / device_count_columns) and the batch
        # kernel's free columns alike
        row = self.node_table.row_of.get(node_id)
        if row is not None:
            for key in [
                k for k in self.node_table.device_used
                if k[0] == row
            ]:
                del self.node_table.device_used[key]
        held: set = set()
        for aid in self._allocs_by_node.get(node_id, ()):
            a = self.allocs[aid]
            if a.terminal_status() or a.allocated_resources is None:
                continue
            values = [
                p.value
                for p in a.allocated_resources.shared.ports
            ]
            for tr in a.allocated_resources.tasks.values():
                for net in tr.networks:
                    values.extend(
                        p.value for p in net.reserved_ports
                    )
                if row is not None:
                    for dv in tr.devices:
                        key = (
                            row,
                            (dv.vendor, dv.type, dv.name),
                        )
                        self.node_table.device_used[key] = (
                            self.node_table.device_used.get(key, 0)
                            + len(dv.device_ids)
                        )
            for value in values:
                if not value or value >= MIN_DYNAMIC_PORT:
                    continue
                by_node = self._ports_live.setdefault(value, {})
                by_node[node_id] = by_node.get(node_id, 0) + 1
                held.add(value)
        if held:
            self._ports_by_node[node_id] = held

    def live_port_nodes(self, port: int) -> Dict[str, int]:
        """node_id -> live alloc count holding `port` (empty when
        free everywhere)."""
        return self._ports_live.get(port, {})

    def usage_delta_since(
        self, generation: int
    ) -> Tuple[int, List[int]]:
        """Atomic (current usage generation, rows dirtied after
        ``generation``) for consumers that mirror the node table's
        usage columns off-host (the BatchWorker's device-resident
        input cache).  Taken under the store lock so a concurrent plan
        apply can't dirty a row between the generation read and the
        row scan — a racing write after release only makes the row
        dirty again at a later generation, so the next delta re-patches
        it with the same values (idempotent)."""
        with self._lock:
            table = self.node_table
            return (
                table.usage_generation,
                table.usage_rows_dirty_since(generation),
            )

    def _touch_node(self, node_id: str) -> None:
        """Bump a node's mutation fingerprint (called under the store
        lock by every write that changes the node's schedulable
        state)."""
        self._node_touch[node_id] = self._node_touch.get(node_id, 0) + 1

    def node_touch_count(self, node_id: str) -> int:
        """Current mutation-fingerprint count for one node.
        Lock-free: counts are ints assigned under the store lock, and
        a racing write only makes a conflict check more
        conservative."""
        return self._node_touch.get(node_id, 0)

    def node_touch_counts(self) -> Dict[str, int]:
        """Snapshot of every node's mutation count (the optimistic
        replay wave's conflict baseline), copied under the lock so it
        is consistent with a single store index."""
        with self._lock:
            return dict(self._node_touch)

    def readiness_generation(self) -> int:
        """Generation of the ready-node set (bumped on join/leave and
        status/eligibility/drain flips, NOT on usage churn) — the
        global fence for speculative replays whose candidate scan
        covers every node."""
        return self._readiness_gen

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self.allocs.get(alloc_id)

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        return [
            self.allocs[aid]
            for aid in self._allocs_by_node.get(node_id, ())
            if aid in self.allocs
        ]

    def allocs_by_node_terminal(
        self, node_id: str, terminal: bool
    ) -> List[Allocation]:
        return [
            a for a in self.allocs_by_node(node_id) if a.terminal_status() == terminal
        ]

    def allocs_by_job(
        self, namespace: str, job_id: str, all_versions: bool = True
    ) -> List[Allocation]:
        return [
            self.allocs[aid]
            for aid in self._allocs_by_job.get((namespace, job_id), ())
            if aid in self.allocs
        ]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        return [
            self.allocs[aid]
            for aid in self._allocs_by_eval.get(eval_id, ())
            if aid in self.allocs
        ]

    # ------------------------------------------------------------------
    # deployments
    # ------------------------------------------------------------------

    def upsert_deployment(self, deployment: Deployment) -> int:
        with self._lock:
            existing = self.deployments.get(deployment.id)
            if existing is not None:
                deployment.create_index = existing.create_index
            else:
                deployment.create_index = self._index + 1
            deployment.modify_index = self._index + 1
            self.deployments[deployment.id] = deployment
            self._deployments_by_job[
                (deployment.namespace, deployment.job_id)
            ].add(deployment.id)
            return self._bump("deployments")

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self.deployments.get(deployment_id)

    def deployments_by_job(
        self, namespace: str, job_id: str
    ) -> List[Deployment]:
        return [
            self.deployments[did]
            for did in self._deployments_by_job.get((namespace, job_id), ())
            if did in self.deployments
        ]

    def latest_deployment_by_job(
        self, namespace: str, job_id: str
    ) -> Optional[Deployment]:
        deployments = self.deployments_by_job(namespace, job_id)
        if not deployments:
            return None
        return max(deployments, key=lambda d: d.create_index)

    # ------------------------------------------------------------------
    # scheduler config
    # ------------------------------------------------------------------

    def get_autopilot_config(self):
        return self.autopilot_config

    def set_autopilot_config(self, config) -> int:
        """(reference state_store.go AutopilotSetConfig; operator
        endpoint writes it through raft)"""
        with self._lock:
            self.autopilot_config = config
            return self._bump("autopilot-config")

    def get_scheduler_config(self) -> SchedulerConfiguration:
        return self.scheduler_config

    def set_scheduler_config(self, config: SchedulerConfiguration) -> int:
        with self._lock:
            self.scheduler_config = config
            return self._bump("scheduler_config")

    # ------------------------------------------------------------------
    # plan results -- the one write path for the scheduler
    # (reference state_store.go:240 UpsertPlanResults)
    # ------------------------------------------------------------------

    def upsert_plan_results(
        self, result: PlanResult, eval_id: str = "",
        leader_gen: Optional[int] = None,
    ) -> int:
        # leader_gen is the replicated-store facade's concern (the FSM
        # leadership fence); the direct single-process store accepts
        # and ignores it so the plan applier can pass one call shape
        with self._lock:
            updates: List[Allocation] = []
            for allocs in result.node_update.values():
                updates.extend(allocs)
            for allocs in result.node_preemptions.values():
                updates.extend(allocs)
            for allocs in result.node_allocation.values():
                updates.extend(allocs)
            self._upsert_allocs_locked(updates)
            # claim CSI volumes for the placements in this plan (the
            # serialized applier is the claim's linearization point;
            # reference claims via CSIVolume.Claim from the client's
            # csi_hook, released by the volume watcher either way)
            for allocs in result.node_allocation.values():
                for alloc in allocs:
                    self._claim_csi_for_alloc_locked(alloc)
            if result.deployment is not None:
                d = result.deployment
                existing = self.deployments.get(d.id)
                if existing is None:
                    d.create_index = self._index + 1
                d.modify_index = self._index + 1
                self.deployments[d.id] = d
                self._deployments_by_job[(d.namespace, d.job_id)].add(d.id)
            for upd in result.deployment_updates:
                d = self.deployments.get(upd.deployment_id)
                if d is not None:
                    d.status = upd.status
                    d.status_description = upd.status_description
                    d.modify_index = self._index + 1
            # record canary placements on the deployment state so later
            # reconcile passes (watcher evals, re-registers) recognize
            # them instead of double-placing canaries / stopping old
            # allocs (reference state_store.go updateDeploymentWithAlloc
            # appending to DeploymentState.PlacedCanaries)
            for allocs in result.node_allocation.values():
                for alloc in allocs:
                    if not (
                        alloc.deployment_id
                        and alloc.deployment_status is not None
                        and alloc.deployment_status.canary
                    ):
                        continue
                    d = self.deployments.get(alloc.deployment_id)
                    if d is None:
                        continue
                    ds = d.task_groups.get(alloc.task_group)
                    if ds is not None and (
                        alloc.id not in ds.placed_canaries
                    ):
                        ds.placed_canaries.append(alloc.id)
            index = self._bump("allocs", "deployments")
            self._notify_alloc_watchers(updates)
            if eval_id:
                # flight recorder: the eval's plan reached durable
                # state at this raft index — the trace's commit mark
                TRACE.event(
                    eval_id, "store.commit", index=index,
                    allocs=len(updates),
                )
            return index

    def _claim_csi_for_alloc_locked(self, alloc: Allocation) -> None:
        job = alloc.job or self.job_by_id(alloc.namespace, alloc.job_id)
        if job is None:
            return
        tg = job.lookup_task_group(alloc.task_group)
        if tg is None:
            return
        for req in tg.volumes.values():
            if req.type != "csi":
                continue
            vol = self.csi_volumes.get((alloc.namespace, req.source))
            if vol is None:
                continue
            if alloc.id in vol.read_claims or alloc.id in vol.write_claims:
                continue
            if vol.claimable(req.read_only):
                vol.claim(alloc.id, alloc.node_id, req.read_only)
                vol.modify_index = self._index + 1

    # ------------------------------------------------------------------
    # job status derivation (reference state_store.go setJobStatus)
    # ------------------------------------------------------------------

    def derive_job_status(self, namespace: str, job_id: str) -> str:
        job = self.job_by_id(namespace, job_id)
        if job is None:
            return JOB_STATUS_DEAD
        allocs = self.allocs_by_job(namespace, job_id)
        evals = self.evals_by_job(namespace, job_id)
        if any(not a.terminal_status() for a in allocs):
            return JOB_STATUS_RUNNING
        if any(not e.terminal_status() for e in evals):
            return JOB_STATUS_PENDING
        if job.stop:
            return JOB_STATUS_DEAD
        if job.type == JOB_TYPE_SYSTEM or job.is_periodic() or job.is_parameterized():
            return JOB_STATUS_RUNNING if not job.stop else JOB_STATUS_DEAD
        if allocs or evals:
            return JOB_STATUS_DEAD
        return JOB_STATUS_PENDING


class StateSnapshot:
    """A read view fenced at an index.

    Mutation is serialized behind the plan applier in this control plane, so
    the snapshot can delegate to the live store; it exists to carry the
    snapshot index (for plan verification ordering) and to present the small
    `State` read surface the schedulers consume
    (reference scheduler/scheduler.go:65-109).
    """

    def __init__(self, store: StateStore, index: int) -> None:
        self._store = store
        self.index = index
        self._job_override: Optional[Job] = None

    def override_job(self, job: Job) -> None:
        """Overlay a not-yet-committed job version on this view (used
        by the plan dry-run so staging never touches the store —
        reference nomad/job_endpoint.go Plan runs on a snapshot)."""
        self._job_override = job

    def latest_index(self) -> int:
        """The snapshot's fence index — lets store consumers that
        only need the read surface plus an index (plan_apply's
        evaluate_plan stamping refresh_index) accept a snapshot."""
        return self.index

    # the scheduler-facing read surface
    def nodes(self) -> List[Node]:
        return list(self._store.iter_nodes())

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._store.node_by_id(node_id)

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        ov = self._job_override
        if ov is not None and (ov.namespace, ov.id) == (namespace, job_id):
            return ov
        return self._store.job_by_id(namespace, job_id)

    def job_by_version(self, namespace: str, job_id: str, version: int):
        return self._store.job_by_version(namespace, job_id, version)

    def allocs_by_job(self, namespace: str, job_id: str) -> List[Allocation]:
        return self._store.allocs_by_job(namespace, job_id)

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        return self._store.allocs_by_node(node_id)

    def allocs_by_node_terminal(self, node_id: str, terminal: bool):
        return self._store.allocs_by_node_terminal(node_id, terminal)

    def live_port_nodes(self, port: int) -> Dict[str, int]:
        return self._store.live_port_nodes(port)

    def node_touch_count(self, node_id: str) -> int:
        return self._store.node_touch_count(node_id)

    def readiness_generation(self) -> int:
        return self._store.readiness_generation()

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._store.alloc_by_id(alloc_id)

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._store.eval_by_id(eval_id)

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        return self._store.evals_by_job(namespace, job_id)

    def deployments_by_job(self, namespace: str, job_id: str):
        return self._store.deployments_by_job(namespace, job_id)

    def latest_deployment_by_job(self, namespace: str, job_id: str):
        return self._store.latest_deployment_by_job(namespace, job_id)

    def scheduler_config(self) -> SchedulerConfiguration:
        return self._store.get_scheduler_config()

    def csi_volume_by_id(
        self, namespace: str, volume_id: str
    ) -> Optional[CSIVolume]:
        return self._store.csi_volume_by_id(namespace, volume_id)

    def iter_csi_volumes(
        self, namespace: Optional[str] = None
    ) -> List[CSIVolume]:
        return self._store.iter_csi_volumes(namespace)

    @property
    def node_table(self) -> NodeTable:
        return self._store.node_table
