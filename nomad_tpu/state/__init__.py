from .store import StateStore, StateSnapshot  # noqa: F401
from .node_table import NodeTable, Interner  # noqa: F401
