"""Columnar mirror of the node table: the TPU-resident "cluster tensor".

This is the structure the whole TPU-first design hangs off (SURVEY.md
section 7.1): every scheduling-relevant node property is kept as a flat
numpy column over a padded row space, so one `jax.jit`-ed kernel can score
*all* candidate nodes at once instead of walking them through the
reference's pull-based iterator chain (scheduler/stack.go:116).

Key ideas:

* **Stable padded capacity.**  Rows live in a fixed-capacity arena that
  grows by doubling, so jit traces stay cached across node joins/leaves;
  vacant rows are simply masked out via the ``active`` column.

* **String interning.**  Node attributes are strings in the reference
  (`Node.Attributes``/``Meta``, feasible.go:713 resolveTarget).  Every
  attribute column interns its values into dense int32 codes (missing =
  -1).  A constraint over any operator — including regex, version and
  semver, the reference's "escaped" cases (feasible.go:776) — compiles to
  a boolean lookup table over the column's (small) vocabulary, evaluated
  host-side with exact reference semantics; on device the check is just
  ``lut[codes]``, a vectorized gather.  This is how *all* constraint
  operators become TPU-friendly without shipping strings to the chip.

* **Incremental usage columns.**  Live cpu/mem/disk usage per node is
  maintained by the state store on alloc transitions, so per-eval scoring
  needs only the (plan-local) delta, mirroring how the reference derives
  `ProposedAllocs` from a snapshot plus the in-flight plan
  (scheduler/context.go:120).
"""
from __future__ import annotations

import itertools
from bisect import bisect_right
from typing import Dict, List, Optional, Sequence, Tuple, TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from ..structs import Node

MISSING = -1
MIN_CAPACITY = 64


class Interner:
    """Dense string -> int32 code assignment, append-only."""

    def __init__(self) -> None:
        self._codes: Dict[str, int] = {}
        self.values: List[str] = []

    def code(self, value: str) -> int:
        c = self._codes.get(value)
        if c is None:
            c = len(self.values)
            self._codes[value] = c
            self.values.append(value)
        return c

    def lookup(self, value: str) -> int:
        return self._codes.get(value, MISSING)

    def __len__(self) -> int:
        return len(self.values)


class _Column:
    """An interned string column over the node arena."""

    def __init__(self, capacity: int) -> None:
        self.codes = np.full(capacity, MISSING, dtype=np.int32)
        self.interner = Interner()

    def grow(self, capacity: int) -> None:
        new = np.full(capacity, MISSING, dtype=np.int32)
        new[: len(self.codes)] = self.codes
        self.codes = new


class NodeTable:
    # process-wide instance epoch: a snapshot restore REPLACES the
    # store's table with a fresh one whose generation counters restart,
    # so consumers keying caches on (generation, capacity) alone could
    # collide with pre-restore state — the epoch disambiguates tables
    _epochs = itertools.count()

    def __init__(self, capacity: int = MIN_CAPACITY) -> None:
        self.epoch = next(NodeTable._epochs)
        self.capacity = capacity
        self.n_rows = 0  # high-water mark of used rows
        self.row_of: Dict[str, int] = {}
        self.node_ids: List[Optional[str]] = [None] * capacity
        self._free_rows: List[int] = []

        self.active = np.zeros(capacity, dtype=bool)
        self.eligible = np.zeros(capacity, dtype=bool)
        # totals are node resources minus node-reserved resources, the
        # denominator of the reference's free-percentage score
        # (funcs.go:computeFreePercentage)
        self.cpu_total = np.zeros(capacity, dtype=np.float64)
        self.mem_total = np.zeros(capacity, dtype=np.float64)
        self.disk_total = np.zeros(capacity, dtype=np.float64)
        self.cpu_used = np.zeros(capacity, dtype=np.float64)
        self.mem_used = np.zeros(capacity, dtype=np.float64)
        self.disk_used = np.zeros(capacity, dtype=np.float64)

        # interned string columns, keyed by resolved target namespace:
        #   "node.id", "node.name", "node.datacenter", "node.class",
        #   "node.computed_class", "attr.<key>", "meta.<key>",
        #   "driver.<name>" (value "1" when present+healthy),
        #   "hostvol.<name>" (value "1"/"ro")
        self.columns: Dict[str, _Column] = {}

        # device inventory: per node, list of (group_sig_code, count);
        # group signatures intern (vendor, type, name, attrs) tuples
        self.device_sigs = Interner()
        self.device_groups: Dict[int, List[Tuple[int, int]]] = {}
        self._device_sig_meta: Dict[int, tuple] = {}
        # (node_row, (vendor,type,name)) -> instances used by live allocs
        self.device_used: Dict[Tuple[int, Tuple[str, str, str]], int] = {}

        self.generation = 0  # bumped on any mutation; device cache key
        # bumped only on node join/leave/attribute/eligibility changes —
        # NOT on usage updates — so per-jobspec candidate/mask caches
        # survive plan commits (usage changes every apply; topology
        # changes orders of magnitude less often)
        self.topo_generation = 0
        # usage-delta log: monotone generation bumped on every usage
        # write, plus row -> generation-last-dirtied.  Consumers that
        # mirror the usage columns (the BatchWorker's device-resident
        # input cache) record the generation they synced at and patch
        # only rows dirtied since, instead of re-shipping all C rows
        # per flush.
        #
        # The query must cost O(rows dirtied since), not O(rows ever
        # dirtied): a follower catching up from a short lag over a
        # million-row arena cannot afford a full scan of the dirty map
        # per flush.  So writes append to a generation-ordered log
        # (parallel int lists, gens nondecreasing) that the query
        # bisects; the map keeps only each row's LATEST generation and
        # drives coalescing — whenever the log grows past twice the
        # map, it is rebuilt from the map (one entry per row, sorted by
        # generation).  Coalescing is lossless for every "dirty since
        # g" query: a row dirtied after g has latest-gen > g, and the
        # latest entry is exactly what survives.  Amortized O(1) per
        # write, log length bounded by 2x rows-currently-dirty.
        self.usage_generation = 0
        self._usage_dirty: Dict[int, int] = {}
        self._usage_log_gens: List[int] = []
        self._usage_log_rows: List[int] = []
        # row -> scheduling-relevant fingerprint of the node last
        # upserted there, for topo-change detection (see upsert_node)
        self._row_fingerprints: Dict[int, tuple] = {}

    # ------------------------------------------------------------------
    # arena management
    # ------------------------------------------------------------------

    def _ensure_capacity(self, needed: int) -> None:
        if needed <= self.capacity:
            return
        new_cap = self.capacity
        while new_cap < needed:
            new_cap *= 2
        for name in (
            "active",
            "eligible",
            "cpu_total",
            "mem_total",
            "disk_total",
            "cpu_used",
            "mem_used",
            "disk_used",
        ):
            old = getattr(self, name)
            new = np.zeros(new_cap, dtype=old.dtype)
            new[: self.capacity] = old
            setattr(self, name, new)
        for col in self.columns.values():
            col.grow(new_cap)
        self.node_ids.extend([None] * (new_cap - self.capacity))
        self.capacity = new_cap

    def _alloc_row(self, node_id: str) -> int:
        if self._free_rows:
            row = self._free_rows.pop()
        else:
            self._ensure_capacity(self.n_rows + 1)
            row = self.n_rows
            self.n_rows += 1
        self.row_of[node_id] = row
        self.node_ids[row] = node_id
        return row

    # ------------------------------------------------------------------
    # column access
    # ------------------------------------------------------------------

    def column(self, key: str) -> _Column:
        """Get or lazily create an interned column, backfilling existing
        rows on first touch."""
        col = self.columns.get(key)
        if col is not None:
            return col
        col = _Column(self.capacity)
        self.columns[key] = col
        # backfill from stored nodes
        for node_id, row in self.row_of.items():
            value = self._raw_value(key, row)
            col.codes[row] = (
                col.interner.code(value) if value is not None else MISSING
            )
        self.generation += 1
        return col

    def _raw_value(self, key: str, row: int) -> Optional[str]:
        node = self._nodes_cache.get(self.node_ids[row]) if hasattr(
            self, "_nodes_cache"
        ) else None
        if node is None:
            return None
        return _resolve_column_value(node, key)

    # ------------------------------------------------------------------
    # mutation
    # ------------------------------------------------------------------

    @staticmethod
    def _node_fingerprint(node: "Node", eligible: bool) -> tuple:
        """Everything about a node that any topology-keyed consumer
        can observe (columns — materialized or lazily created later —
        candidate sets, port-reservation columns, device inventory).
        If this tuple is unchanged, re-upserting the node cannot
        change any scheduling decision."""
        res = node.node_resources
        reserved = node.reserved_resources
        return (
            node.name,
            node.datacenter,
            node.node_class,
            node.computed_class,
            eligible,
            float(res.cpu - reserved.cpu),
            float(res.memory_mb - reserved.memory_mb),
            float(res.disk_mb - reserved.disk_mb),
            tuple(sorted(node.attributes.items())),
            tuple(sorted(node.meta.items())),
            tuple(
                sorted(
                    (k, bool(v)) for k, v in node.drivers.items()
                )
            ),
            tuple(
                sorted(
                    (k, v.read_only)
                    for k, v in node.host_volumes.items()
                )
            ),
            tuple(
                sorted(
                    (k, bool(v))
                    for k, v in node.csi_node_plugins.items()
                )
            ),
            tuple(sorted(reserved.reserved_ports)),
            tuple(
                (
                    net.mode or "host",
                    net.ip or "",
                    tuple(
                        sorted(p.value for p in net.reserved_ports)
                    ),
                )
                for net in res.networks
            ),
            tuple(
                (
                    g.vendor,
                    g.type,
                    g.name,
                    tuple(
                        sorted(
                            (k, str(v))
                            for k, v in g.attributes.items()
                        )
                    ),
                    tuple(g.instance_ids),
                )
                for g in res.devices
            ),
        )

    def upsert_node(self, node: "Node") -> int:
        if not hasattr(self, "_nodes_cache"):
            self._nodes_cache: Dict[str, "Node"] = {}
        self._nodes_cache[node.id] = node
        row = self.row_of.get(node.id)
        changed = row is None  # join = topology change by definition
        if row is None:
            row = self._alloc_row(node.id)
        eligible = node.ready()
        # topology change detection: heartbeats and periodic
        # fingerprints re-upsert nodes with UNCHANGED state every few
        # seconds; bumping topo_generation for those would thrash
        # every topology-keyed cache downstream (candidate/mask/port
        # columns, the BatchWorker's device-resident input mirror), so
        # the bump happens only when the node's scheduling-relevant
        # fingerprint actually moves
        fp = self._node_fingerprint(node, eligible)
        changed |= self._row_fingerprints.get(row) != fp
        self._row_fingerprints[row] = fp
        self.active[row] = True
        self.eligible[row] = eligible
        res = node.node_resources
        reserved = node.reserved_resources
        self.cpu_total[row] = float(res.cpu - reserved.cpu)
        self.mem_total[row] = float(res.memory_mb - reserved.memory_mb)
        self.disk_total[row] = float(res.disk_mb - reserved.disk_mb)
        for key, col in self.columns.items():
            value = _resolve_column_value(node, key)
            col.codes[row] = (
                col.interner.code(value) if value is not None else MISSING
            )
        groups: List[Tuple[int, int]] = []
        for g in res.devices:
            sig = (
                g.vendor,
                g.type,
                g.name,
                tuple(sorted((k, str(v)) for k, v in g.attributes.items())),
            )
            code = self.device_sigs.code(repr(sig))
            self._device_sig_meta[code] = sig
            groups.append((code, len(g.instance_ids)))
        if groups or row in self.device_groups:
            self.device_groups[row] = groups
        self.generation += 1
        if changed:
            self.topo_generation += 1
        return row

    def delete_node(self, node_id: str) -> None:
        row = self.row_of.pop(node_id, None)
        if row is None:
            return
        self.active[row] = False
        self.eligible[row] = False
        self.cpu_used[row] = self.mem_used[row] = self.disk_used[row] = 0.0
        self.usage_generation += 1
        self._log_usage_dirty(row)
        self.node_ids[row] = None
        self.device_groups.pop(row, None)
        self._row_fingerprints.pop(row, None)
        # a reused row must not inherit phantom device reservations
        for key in [k for k in self.device_used if k[0] == row]:
            del self.device_used[key]
        if hasattr(self, "_nodes_cache"):
            self._nodes_cache.pop(node_id, None)
        self._free_rows.append(row)
        self.generation += 1
        self.topo_generation += 1

    def update_node_usage(
        self, node_id: str, usage: Tuple[int, int, int]
    ) -> None:
        row = self.row_of.get(node_id)
        if row is None:
            return
        self.cpu_used[row] = float(usage[0])
        self.mem_used[row] = float(usage[1])
        self.disk_used[row] = float(usage[2])
        self.generation += 1
        self.usage_generation += 1
        self._log_usage_dirty(row)

    def _log_usage_dirty(self, row: int) -> None:
        """Record ``row`` as dirtied at the CURRENT usage_generation
        (caller bumps first) and coalesce the log when it outgrows the
        per-row map."""
        self._usage_dirty[row] = self.usage_generation
        self._usage_log_gens.append(self.usage_generation)
        self._usage_log_rows.append(row)
        if (
            len(self._usage_log_gens) > 64
            and len(self._usage_log_gens) > 2 * len(self._usage_dirty)
        ):
            self.compact_usage_log()

    def compact_usage_log(self) -> None:
        """Coalesce the usage-delta log down to one entry per dirty
        row (its latest generation), preserving generation order."""
        items = sorted(self._usage_dirty.items(), key=lambda kv: kv[1])
        self._usage_log_rows = [row for row, _ in items]
        self._usage_log_gens = [g for _, g in items]

    def usage_log_len(self) -> int:
        """Current (possibly uncoalesced) log length — observability
        for the compaction tests and the bigworld accounting."""
        return len(self._usage_log_gens)

    def usage_rows_dirty_since(self, generation: int) -> List[int]:
        """Rows whose usage columns changed after ``generation``, in
        O(log L + rows-dirtied-since) via a bisect on the
        generation-ordered log (duplicates coalesced).  Callers needing
        atomicity against concurrent writers go through
        ``StateStore.usage_delta_since`` (takes the store lock)."""
        i = bisect_right(self._usage_log_gens, generation)
        if i == len(self._usage_log_gens):
            return []
        return list(dict.fromkeys(self._usage_log_rows[i:]))

    # ------------------------------------------------------------------
    # bulk (columnar) registration — the bigworld seeding path
    # ------------------------------------------------------------------

    def bulk_register_nodes(self, nodes: Sequence["Node"]) -> np.ndarray:
        """Register many FRESH nodes in one columnar pass.

        The per-node ``upsert_node`` costs a scheduling fingerprint
        (a ~1KB tuple kept per row for topo-change detection) plus a
        per-call generation bump; at a million rows the fingerprints
        alone are a gigabyte and the column writes dominate seed time.
        This path assigns one contiguous row block, fills the numpy
        columns with sliced writes, and skips the fingerprints
        entirely — a later real ``upsert_node`` of the same id sees a
        fingerprint miss and bumps ``topo_generation``, which is the
        conservative (correct) direction.  Caller guarantees no id is
        already registered.  All new rows are marked usage-dirty under
        a single generation so delta mirrors pick them up.
        """
        n = len(nodes)
        if n == 0:
            return np.zeros(0, dtype=np.int32)
        if not hasattr(self, "_nodes_cache"):
            self._nodes_cache: Dict[str, "Node"] = {}
        self._ensure_capacity(self.n_rows + n)
        start = self.n_rows
        self.n_rows += n
        ids = [node.id for node in nodes]
        self.row_of.update(zip(ids, range(start, start + n)))
        self.node_ids[start : start + n] = ids
        self._nodes_cache.update(zip(ids, nodes))
        self.active[start : start + n] = True
        cpu = np.empty(n, dtype=np.float64)
        mem = np.empty(n, dtype=np.float64)
        disk = np.empty(n, dtype=np.float64)
        elig = np.empty(n, dtype=bool)
        for i, node in enumerate(nodes):
            res = node.node_resources
            reserved = node.reserved_resources
            cpu[i] = res.cpu - reserved.cpu
            mem[i] = res.memory_mb - reserved.memory_mb
            disk[i] = res.disk_mb - reserved.disk_mb
            elig[i] = node.ready()
            if res.devices:
                groups: List[Tuple[int, int]] = []
                for g in res.devices:
                    sig = (
                        g.vendor,
                        g.type,
                        g.name,
                        tuple(
                            sorted(
                                (k, str(v))
                                for k, v in g.attributes.items()
                            )
                        ),
                    )
                    code = self.device_sigs.code(repr(sig))
                    self._device_sig_meta[code] = sig
                    groups.append((code, len(g.instance_ids)))
                self.device_groups[start + i] = groups
        self.eligible[start : start + n] = elig
        self.cpu_total[start : start + n] = cpu
        self.mem_total[start : start + n] = mem
        self.disk_total[start : start + n] = disk
        for key, col in self.columns.items():
            for i, node in enumerate(nodes):
                value = _resolve_column_value(node, key)
                col.codes[start + i] = (
                    col.interner.code(value)
                    if value is not None
                    else MISSING
                )
        self.generation += 1
        self.topo_generation += 1
        self.usage_generation += 1
        g = self.usage_generation
        rows = range(start, start + n)
        self._usage_dirty.update(dict.fromkeys(rows, g))
        self._usage_log_gens.extend([g] * n)
        self._usage_log_rows.extend(rows)
        return np.arange(start, start + n, dtype=np.int32)

    def bulk_set_usage(
        self,
        rows: np.ndarray,
        cpu: np.ndarray,
        mem: np.ndarray,
        disk: np.ndarray,
    ) -> None:
        """Vectorized usage write for many rows under ONE generation —
        the seeding path's counterpart of ``update_node_usage`` (which
        costs a generation bump and a log append per row)."""
        if len(rows) == 0:
            return
        self.cpu_used[rows] = cpu
        self.mem_used[rows] = mem
        self.disk_used[rows] = disk
        self.generation += 1
        self.usage_generation += 1
        g = self.usage_generation
        row_list = np.asarray(rows).tolist()
        self._usage_dirty.update(dict.fromkeys(row_list, g))
        self._usage_log_gens.extend([g] * len(row_list))
        self._usage_log_rows.extend(row_list)
        if (
            len(self._usage_log_gens) > 64
            and len(self._usage_log_gens) > 2 * len(self._usage_dirty)
        ):
            self.compact_usage_log()

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------

    def rows_for(self, node_ids: List[str]) -> np.ndarray:
        return np.array(
            [self.row_of[nid] for nid in node_ids if nid in self.row_of],
            dtype=np.int32,
        )

    def device_sig_key(self, code: int) -> tuple:
        """(vendor, type, name) of a device-sig code — the key shape
        AllocatedDeviceResource records carry."""
        sig = self._device_sig_meta[code]
        return (sig[0], sig[1], sig[2])

    def device_sig_matches(self, code: int, ask_name: str) -> bool:
        """Whether an interned device-group signature matches a device ask
        of the form type | vendor/type | vendor/type/name."""
        sig = self._device_sig_meta.get(code)
        if sig is None:
            return False
        vendor, type_, name, _attrs = sig
        parts = ask_name.split("/")
        if len(parts) == 1:
            return parts[0] == type_
        if len(parts) == 2:
            return parts[0] == vendor and parts[1] == type_
        return (
            parts[0] == vendor
            and parts[1] == type_
            and "/".join(parts[2:]) == name
        )

    def device_sig_attrs(self, code: int) -> Dict[str, str]:
        sig = self._device_sig_meta.get(code)
        if sig is None:
            return {}
        return dict(sig[3])

    def device_count_columns(self, ask_name: str) -> Tuple[np.ndarray, np.ndarray]:
        """(total_matching, used_matching) instance counts per row for a
        device ask (constraint filtering applied separately via sig LUTs)."""
        total = np.zeros(self.capacity, dtype=np.int32)
        used = np.zeros(self.capacity, dtype=np.int32)
        matching_codes = {
            code
            for code in range(len(self.device_sigs))
            if self.device_sig_matches(code, ask_name)
        }
        for row, groups in self.device_groups.items():
            for code, count in groups:
                if code in matching_codes:
                    total[row] += count
        for (row, key), count in self.device_used.items():
            vendor, type_, name = key
            probe = "/".join(x for x in (vendor, type_, name) if x)
            # conservative: count used instances whose group matches the ask
            for code in matching_codes:
                sig = self._device_sig_meta[code]
                if (sig[0], sig[1], sig[2]) == key:
                    used[row] += count
                    break
        return total, used


def _resolve_column_value(node: "Node", key: str) -> Optional[str]:
    """Resolve a column key to the node's string value; None == missing.
    Mirrors the reference's target interpolation (feasible.go:713
    resolveTarget) plus synthetic driver/hostvol namespaces."""
    if key == "node.id":
        return node.id
    if key == "node.name":
        return node.name
    if key == "node.datacenter":
        return node.datacenter
    if key == "node.class":
        return node.node_class
    if key == "node.computed_class":
        return node.computed_class
    if key.startswith("attr."):
        return node.attributes.get(key[len("attr.") :])
    if key.startswith("meta."):
        return node.meta.get(key[len("meta.") :])
    if key.startswith("driver."):
        name = key[len("driver.") :]
        healthy = node.drivers.get(name)
        if healthy is None:
            # fall back to the detected-driver attribute form the
            # fingerprinter writes (reference feasible.go:430)
            attr = node.attributes.get(f"driver.{name}")
            return "1" if attr not in (None, "", "0", "false") else None
        return "1" if healthy else None
    if key.startswith("hostvol."):
        name = key[len("hostvol.") :]
        vol = node.host_volumes.get(name)
        if vol is None:
            return None
        return "ro" if vol.read_only else "rw"
    if key.startswith("csi."):
        name = key[len("csi.") :]
        healthy = node.csi_node_plugins.get(name)
        return "1" if healthy else None
    if key.startswith("netmode."):
        mode = key[len("netmode.") :]
        for net in node.node_resources.networks:
            if (net.mode or "host") == mode:
                return "1"
        # host mode is implicitly available on every node
        return "1" if mode == "host" else None
    return None
