"""Resource math: fit checks and fitness scoring.

Host-side reference implementations with the exact semantics of the
reference's `nomad/structs/funcs.go` (AllocsFit:103, ScoreFitBinPack:175,
ScoreFitSpread:202).  The vectorized device versions live in
`nomad_tpu/ops/score.py`; these scalar forms are the parity oracle and the
plan-applier recheck path.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from .structs import (
    Allocation,
    ComparableResources,
    Node,
)
from .network import NetworkIndex

# Maximum possible bin-packing fitness score; used to normalize to [0, 1]
# (reference scheduler/rank.go:13).
BINPACK_MAX_FIT_SCORE = 18.0


def filter_terminal_allocs(
    allocs: List[Allocation],
) -> Tuple[List[Allocation], Dict[str, Allocation]]:
    """Split out terminal allocations, keeping only the latest terminal
    allocation per name (reference funcs.go:FilterTerminalAllocs)."""
    terminal: Dict[str, Allocation] = {}
    live: List[Allocation] = []
    for alloc in allocs:
        if alloc.terminal_status():
            prev = terminal.get(alloc.name)
            if prev is None or alloc.create_index > prev.create_index:
                terminal[alloc.name] = alloc
        else:
            live.append(alloc)
    return live, terminal


def remove_allocs(
    allocs: List[Allocation], remove: List[Allocation]
) -> List[Allocation]:
    """(reference funcs.go:RemoveAllocs)"""
    drop = {a.id for a in remove}
    return [a for a in allocs if a.id not in drop]


def allocs_fit(
    node: Node,
    allocs: List[Allocation],
    net_idx: Optional[NetworkIndex] = None,
    check_devices: bool = False,
) -> Tuple[bool, str, ComparableResources]:
    """Check whether a set of allocations fits on a node.

    Returns (fit, exhausted_dimension, used).  Terminal allocations are
    ignored (reference funcs.go:103 AllocsFit).
    """
    used = ComparableResources()
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        used.add(alloc.comparable_resources())

    available = node.comparable_resources()
    available.subtract(node.comparable_reserved_resources())
    ok, dim = available.superset(used)
    if not ok:
        return False, dim, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node) or net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    if check_devices:
        from .device_accounting import DeviceAccounter

        accounter = DeviceAccounter(node)
        if accounter.add_allocs(allocs):
            return False, "device oversubscribed", used

    return True, "", used


def compute_free_percentage(
    node: Node, util: ComparableResources
) -> Tuple[float, float]:
    """Free cpu/mem fractions after subtracting node-reserved resources
    (reference funcs.go:computeFreePercentage)."""
    res = node.comparable_resources()
    reserved = node.comparable_reserved_resources()
    node_cpu = float(res.cpu) - float(reserved.cpu)
    node_mem = float(res.memory_mb) - float(reserved.memory_mb)
    free_pct_cpu = 1.0 - (float(util.cpu) / node_cpu)
    free_pct_ram = 1.0 - (float(util.memory_mb) / node_mem)
    return free_pct_cpu, free_pct_ram


def _pow10(x: float) -> float:
    """Canonical 10^x for fitness scoring: the f64 result rounds
    through float32.

    libm (host) and XLA (kernel) disagree by 1 f64 ulp on ~5% of
    inputs, so raw-f64 exponentials make bit-identical host/accelerator
    decisions impossible in principle.  The framework therefore DEFINES
    the fitness exponential at float32 precision on every
    implementation — the two sides' 1-ulp f64 differences collapse to
    the same f32 value, and all downstream arithmetic stays exact f64.
    (Decision drift vs the reference's raw-f64 math is confined to
    scores closer than ~1e-7, where the reference's own ordering is
    implementation-defined anyway.)"""
    return float(np.float32(math.pow(10.0, x)))


def pow10_np(x: "np.ndarray") -> "np.ndarray":
    """Vectorized canonical 10^x (same f32 rounding as _pow10) for
    numpy score paths that must stay bit-identical to the scalar host
    and jnp kernel implementations."""
    return np.float32(np.power(10.0, x)).astype(np.float64)


def score_fit_binpack(node: Node, util: ComparableResources) -> float:
    """Bin-packing fitness in [0, 18]: ``20 - (10^freeCpu + 10^freeRam)``
    ("BestFit v3"; reference funcs.go:175 ScoreFitBinPack)."""
    free_cpu, free_ram = compute_free_percentage(node, util)
    total = _pow10(free_cpu) + _pow10(free_ram)
    score = 20.0 - total
    if score > 18.0:
        score = 18.0
    elif score < 0.0:
        score = 0.0
    return score


def score_fit_spread(node: Node, util: ComparableResources) -> float:
    """Worst-fit (spread) fitness in [0, 18]
    (reference funcs.go:202 ScoreFitSpread)."""
    free_cpu, free_ram = compute_free_percentage(node, util)
    total = _pow10(free_cpu) + _pow10(free_ram)
    score = total - 2.0
    if score > 18.0:
        score = 18.0
    elif score < 0.0:
        score = 0.0
    return score


def net_priority(priorities: List[int]) -> float:
    """Aggregate priority of a preempted-alloc set: max plus the ratio of
    sum to max (reference scheduler/rank.go:750 netPriority)."""
    if not priorities:
        return 0.0
    mx = float(max(priorities))
    sm = float(sum(priorities))
    return mx + (sm / mx)


def preemption_score(netp: float) -> float:
    """Logistic score in (0, 1); 0.5 at netPriority 2048
    (reference scheduler/rank.go:773 preemptionScore)."""
    rate = 0.0048
    origin = 2048.0
    return 1.0 / (1.0 + math.exp(rate * (netp - origin)))
