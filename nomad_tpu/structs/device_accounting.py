"""Device instance accounting (reference nomad/structs/devices.go
DeviceAccounter): tracks which device instances on a node are in use and
detects oversubscription.
"""
from __future__ import annotations

from typing import Dict, List, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .structs import Allocation, Node


class DeviceAccounter:
    def __init__(self, node: "Node") -> None:
        # (vendor, type, name) -> {instance_id: used_count}
        self.devices: Dict[tuple, Dict[str, int]] = {}
        for group in node.node_resources.devices:
            key = (group.vendor, group.type, group.name)
            self.devices[key] = {iid: 0 for iid in group.instance_ids}

    def add_allocs(self, allocs: List["Allocation"]) -> bool:
        """Mark instances used by the allocations; returns True if any
        instance is used more than once or is unknown (collision)."""
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            ar = alloc.allocated_resources
            if ar is None:
                continue
            for tr in ar.tasks.values():
                for dev in tr.devices:
                    key = (dev.vendor, dev.type, dev.name)
                    group = self.devices.get(key)
                    if group is None:
                        collide = True
                        continue
                    for iid in dev.device_ids:
                        if iid not in group:
                            collide = True
                        else:
                            group[iid] += 1
                            if group[iid] > 1:
                                collide = True
        return collide

    def add_reserved(self, vendor: str, type_: str, name: str, ids: List[str]) -> bool:
        group = self.devices.get((vendor, type_, name))
        if group is None:
            return True
        collide = False
        for iid in ids:
            if iid not in group:
                collide = True
            else:
                group[iid] += 1
                if group[iid] > 1:
                    collide = True
        return collide

    def free_instances(self, vendor: str, type_: str, name: str) -> List[str]:
        group = self.devices.get((vendor, type_, name), {})
        return [iid for iid, used in group.items() if used == 0]
