"""Per-node network port/bandwidth ledger.

Semantic equivalent of the reference's `nomad/structs/network.go:35
NetworkIndex`: tracks used ports per host IP, detects static-port
collisions, and offers port assignments for task-group network asks.

Differences from the reference, chosen deliberately:
  * dynamic ports are assigned deterministically (lowest free port in the
    dynamic range) instead of stochastically — placement *feasibility* is
    unchanged and determinism helps the differential test suite;
  * bandwidth overcommit always reports False, matching the reference where
    bandwidth accounting is deprecated (network.go:79 Overcommitted).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .structs import Allocation, NetworkResource, Node

MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000


@dataclass
class AssignedPort:
    label: str = ""
    value: int = 0
    to: int = 0
    host_ip: str = ""


class NetworkIndex:
    def __init__(self) -> None:
        # ip -> set of used port numbers
        self.used_ports: Dict[str, Set[int]] = {}
        self.avail_bandwidth: Dict[str, int] = {}
        self.used_bandwidth: Dict[str, int] = {}
        self.node_ips: List[str] = []

    # -- setup ------------------------------------------------------------

    def set_node(self, node: "Node") -> bool:
        """Register the node's networks; returns True on collision among the
        node's own reserved ports."""
        collide = False
        for net in node.node_resources.networks:
            if net.device:
                self.avail_bandwidth[net.device] = net.mbits
            ip = net.ip or "0.0.0.0"
            if ip not in self.node_ips:
                self.node_ips.append(ip)
            for port in net.reserved_ports:
                if self._reserve(ip, port.value):
                    collide = True
        if not self.node_ips:
            self.node_ips.append("0.0.0.0")
        for port in node.reserved_resources.reserved_ports:
            if self._reserve(self.node_ips[0], port):
                collide = True
        return collide

    def add_allocs(self, allocs: List["Allocation"]) -> bool:
        """Track ports used by existing (non-terminal) allocations."""
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            ar = alloc.allocated_resources
            if ar is None:
                continue
            for port in ar.shared.ports:
                if self._reserve(port.host_ip or self._default_ip(), port.value):
                    collide = True
            for net in ar.shared.networks:
                if self._add_reserved(net):
                    collide = True
            for tr in ar.tasks.values():
                for net in tr.networks:
                    if self._add_reserved(net):
                        collide = True
        return collide

    def add_reserved(self, net: "NetworkResource") -> bool:
        return self._add_reserved(net)

    def add_reserved_ports(self, ports: List[AssignedPort]) -> bool:
        collide = False
        for p in ports:
            if self._reserve(p.host_ip or self._default_ip(), p.value):
                collide = True
        return collide

    # -- queries ----------------------------------------------------------

    def overcommitted(self) -> bool:
        # Bandwidth accounting is deprecated in the reference
        # (network.go:79); feasibility is port-driven.
        return False

    # -- assignment -------------------------------------------------------

    def assign_ports(self, ask: "NetworkResource") -> Optional[List[AssignedPort]]:
        """Offer host ports for a group-level network ask; None if a static
        port is taken (reference network.go:316 AssignPorts)."""
        ip = self._default_ip()
        used = self.used_ports.setdefault(ip, set())
        offer: List[AssignedPort] = []
        staged: Set[int] = set()

        for port in ask.reserved_ports:
            if port.value in used or port.value in staged:
                return None
            staged.add(port.value)
            offer.append(
                AssignedPort(
                    label=port.label, value=port.value, to=port.to, host_ip=ip
                )
            )

        for port in ask.dynamic_ports:
            value = self._next_dynamic(used, staged)
            if value is None:
                return None
            staged.add(value)
            to = port.to if port.to else value
            offer.append(
                AssignedPort(label=port.label, value=value, to=to, host_ip=ip)
            )
        return offer

    def assign_network(self, ask: "NetworkResource") -> Optional["NetworkResource"]:
        """Offer an interface + ports for a task-level network ask
        (reference network.go:406 AssignNetwork)."""
        from .structs import NetworkResource, Port  # local to avoid cycle

        ip = self._default_ip()
        used = self.used_ports.setdefault(ip, set())
        staged: Set[int] = set()

        reserved: List[Port] = []
        for port in ask.reserved_ports:
            if port.value in used or port.value in staged:
                return None
            staged.add(port.value)
            reserved.append(
                Port(label=port.label, value=port.value, to=port.to)
            )

        dynamic: List[Port] = []
        for port in ask.dynamic_ports:
            value = self._next_dynamic(used, staged)
            if value is None:
                return None
            staged.add(value)
            dynamic.append(Port(label=port.label, value=value, to=port.to))

        offer = NetworkResource(
            mode=ask.mode,
            ip=ip,
            mbits=ask.mbits,
            reserved_ports=reserved,
            dynamic_ports=dynamic,
        )
        if ask.mbits:
            device = ask.device or (
                next(iter(self.avail_bandwidth)) if self.avail_bandwidth else ""
            )
            self.used_bandwidth[device] = (
                self.used_bandwidth.get(device, 0) + ask.mbits
            )
        return offer

    # -- internals --------------------------------------------------------

    def _default_ip(self) -> str:
        return self.node_ips[0] if self.node_ips else "0.0.0.0"

    def _reserve(self, ip: str, port: int) -> bool:
        if port <= 0:
            return False
        used = self.used_ports.setdefault(ip, set())
        if port in used:
            return True
        used.add(port)
        return False

    def _add_reserved(self, net: "NetworkResource") -> bool:
        collide = False
        ip = net.ip or self._default_ip()
        for port in list(net.reserved_ports) + list(net.dynamic_ports):
            if self._reserve(ip, port.value):
                collide = True
        if net.mbits and net.device:
            self.used_bandwidth[net.device] = (
                self.used_bandwidth.get(net.device, 0) + net.mbits
            )
        return collide

    @staticmethod
    def _next_dynamic(used: Set[int], staged: Set[int]) -> Optional[int]:
        for candidate in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT):
            if candidate not in used and candidate not in staged:
                return candidate
        return None
