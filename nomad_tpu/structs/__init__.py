from .structs import *  # noqa: F401,F403
from .funcs import (  # noqa: F401
    score_fit_binpack,
    score_fit_spread,
    compute_free_percentage,
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
)
from .network import NetworkIndex, AssignedPort  # noqa: F401
from .node_class import (  # noqa: F401
    compute_node_class,
    constraint_escapes_class,
    escaped_constraints,
)
