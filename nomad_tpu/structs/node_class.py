"""Computed node class: a hash identifying nodes with identical scheduling-
relevant attributes, used to memoize feasibility results per class
(reference nomad/structs/node_class.go:31 ComputeClass, :108
EscapedConstraints).
"""
from __future__ import annotations

import hashlib
import json
from typing import TYPE_CHECKING, Iterable, List

if TYPE_CHECKING:  # pragma: no cover
    from .structs import Constraint, Node

UNIQUE_NAMESPACE = "unique."


def is_unique_namespace(key: str) -> bool:
    return key.startswith(UNIQUE_NAMESPACE)


def compute_node_class(node: "Node") -> str:
    """Hash the node's non-unique scheduling-relevant fields: datacenter,
    class, attributes, meta (minus ``unique.*`` keys) and device inventory.
    """
    payload = {
        "datacenter": node.datacenter,
        "node_class": node.node_class,
        "attributes": {
            k: v
            for k, v in sorted(node.attributes.items())
            if not is_unique_namespace(k)
        },
        "meta": {
            k: v
            for k, v in sorted(node.meta.items())
            if not is_unique_namespace(k)
        },
        "devices": sorted(
            (
                d.vendor,
                d.type,
                d.name,
                tuple(
                    sorted(
                        (k, str(v))
                        for k, v in d.attributes.items()
                        if not is_unique_namespace(k)
                    )
                ),
            )
            for d in node.node_resources.devices
        ),
    }
    digest = hashlib.sha1(
        json.dumps(payload, sort_keys=True, default=str).encode()
    ).hexdigest()
    return f"v1:{digest[:16]}"


def _target_escapes(target: str) -> bool:
    return (
        target.startswith("${node.unique.")
        or target.startswith("${attr.unique.")
        or target.startswith("${meta.unique.")
    )


def constraint_escapes_class(constraint: "Constraint") -> bool:
    """Whether a constraint targets uniquely-identifying state and therefore
    must bypass computed-class memoization."""
    return _target_escapes(constraint.ltarget) or _target_escapes(
        constraint.rtarget
    )


def escaped_constraints(constraints: Iterable["Constraint"]) -> List["Constraint"]:
    return [c for c in constraints if constraint_escapes_class(c)]
