"""Core data model.

Semantically mirrors the reference's `nomad/structs/structs.go` (Job:3748,
TaskGroup:5495, Task:6152, Node:1720, Allocation:8519, Evaluation:9512,
Plan:9805) without being a field-for-field port: only the state the
scheduler, reconciler, plan applier and client runtime consume is modeled,
and collections are plain Python containers rather than msgpack-codec
structs.  IDs are strings (uuid4 hex by default).
"""
from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Constants (reference: nomad/structs/structs.go)
# ---------------------------------------------------------------------------

JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_CORE = "_core"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

DEFAULT_NAMESPACE = "default"
DEFAULT_REGION = "global"

JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100

NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

NODE_SCHED_ELIGIBLE = "eligible"
NODE_SCHED_INELIGIBLE = "ineligible"

ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

ALLOC_CLIENT_STATUS_PENDING = "pending"
ALLOC_CLIENT_STATUS_RUNNING = "running"
ALLOC_CLIENT_STATUS_COMPLETE = "complete"
ALLOC_CLIENT_STATUS_FAILED = "failed"
ALLOC_CLIENT_STATUS_LOST = "lost"

EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_PERIODIC = "periodic-job"
EVAL_TRIGGER_NODE_DRAIN = "node-drain"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_ALLOC_STOP = "alloc-stop"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
EVAL_TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
EVAL_TRIGGER_FAILED_FOLLOW_UP = "failed-follow-up"
EVAL_TRIGGER_MAX_PLANS = "max-plan-attempts"
EVAL_TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
EVAL_TRIGGER_QUEUED_ALLOCS = "queued-allocs"
EVAL_TRIGGER_PREEMPTION = "preemption"
EVAL_TRIGGER_SCALING = "job-scaling"

# Constraint operands (reference: structs.go Constraint*)
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SEMVER = "semver"
CONSTRAINT_SET_CONTAINS = "set_contains"
CONSTRAINT_SET_CONTAINS_ALL = "set_contains_all"
CONSTRAINT_SET_CONTAINS_ANY = "set_contains_any"
CONSTRAINT_ATTRIBUTE_IS_SET = "is_set"
CONSTRAINT_ATTRIBUTE_IS_NOT_SET = "is_not_set"

SCHEDULER_ALGORITHM_BINPACK = "binpack"
SCHEDULER_ALGORITHM_SPREAD = "spread"

# Deployment statuses (reference: structs.go Deployment*)
DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"

# The maximum priority delta required before an alloc may be preempted
# (reference: scheduler/preemption.go:673).
PREEMPTION_PRIORITY_DELTA = 10


def new_id() -> str:
    return uuid.uuid4().hex


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------


@dataclass
class Port:
    label: str = ""
    value: int = 0  # static port; 0 => dynamic
    to: int = 0
    host_network: str = "default"


@dataclass
class NetworkResource:
    """A network ask/offer (reference structs.go NetworkResource)."""

    mode: str = "host"
    device: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: List[Port] = field(default_factory=list)
    dynamic_ports: List[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return NetworkResource(
            mode=self.mode,
            device=self.device,
            ip=self.ip,
            mbits=self.mbits,
            reserved_ports=[replace(p) for p in self.reserved_ports],
            dynamic_ports=[replace(p) for p in self.dynamic_ports],
        )

    def port_labels(self) -> Dict[str, int]:
        out = {}
        for p in self.reserved_ports:
            out[p.label] = p.value
        for p in self.dynamic_ports:
            out[p.label] = p.value
        return out


@dataclass
class DeviceIdTuple:
    vendor: str = ""
    type: str = ""
    name: str = ""

    def matches(self, ask: str) -> bool:
        """Match an ask of the form "type", "vendor/type" or
        "vendor/type/name" (reference structs.go RequestedDevice.ID)."""
        parts = ask.split("/")
        if len(parts) == 1:
            return parts[0] == self.type
        if len(parts) == 2:
            return parts[0] == self.vendor and parts[1] == self.type
        return (
            parts[0] == self.vendor
            and parts[1] == self.type
            and "/".join(parts[2:]) == self.name
        )


@dataclass
class NodeDeviceResource:
    """A group of homogeneous device instances on a node."""

    vendor: str = ""
    type: str = ""
    name: str = ""
    instance_ids: List[str] = field(default_factory=list)
    attributes: Dict[str, Any] = field(default_factory=dict)

    def id(self) -> DeviceIdTuple:
        return DeviceIdTuple(self.vendor, self.type, self.name)


@dataclass
class RequestedDevice:
    """A task's device ask (reference structs.go RequestedDevice)."""

    name: str = ""  # "type", "vendor/type", or "vendor/type/name"
    count: int = 1
    constraints: List["Constraint"] = field(default_factory=list)
    affinities: List["Affinity"] = field(default_factory=list)


@dataclass
class AllocatedDeviceResource:
    vendor: str = ""
    type: str = ""
    name: str = ""
    device_ids: List[str] = field(default_factory=list)


@dataclass
class Resources:
    """A task's resource ask (reference structs.go Resources:2059)."""

    cpu: int = 100  # MHz shares
    memory_mb: int = 300
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[RequestedDevice] = field(default_factory=list)


@dataclass
class NodeReservedResources:
    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_ports: List[int] = field(default_factory=list)


@dataclass
class NodeResources:
    """Total resources on a node (reference structs.go NodeResources)."""

    cpu: int = 4000
    memory_mb: int = 8192
    disk_mb: int = 100 * 1024
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[NodeDeviceResource] = field(default_factory=list)


@dataclass
class AllocatedTaskResources:
    cpu: int = 0
    memory_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[AllocatedDeviceResource] = field(default_factory=list)


@dataclass
class AllocatedSharedResources:
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    ports: List["AssignedPortData"] = field(default_factory=list)


@dataclass
class AssignedPortData:
    label: str = ""
    value: int = 0
    to: int = 0
    host_ip: str = ""


@dataclass
class AllocatedResources:
    """Resources granted to an allocation, per task plus shared
    (reference structs.go AllocatedResources:2470)."""

    tasks: Dict[str, AllocatedTaskResources] = field(default_factory=dict)
    shared: AllocatedSharedResources = field(default_factory=AllocatedSharedResources)

    def comparable(self) -> "ComparableResources":
        c = ComparableResources()
        for tr in self.tasks.values():
            c.cpu += tr.cpu
            c.memory_mb += tr.memory_mb
            for net in tr.networks:
                c.network_mbits += net.mbits
        c.disk_mb = self.shared.disk_mb
        for net in self.shared.networks:
            c.network_mbits += net.mbits
        return c


@dataclass
class ComparableResources:
    """Flattened cpu/mem + shared disk used for fit checks and scoring
    (reference structs.go ComparableResources / funcs.go AllocsFit)."""

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    network_mbits: int = 0

    def add(self, other: "ComparableResources") -> None:
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb
        self.network_mbits += other.network_mbits

    def subtract(self, other: "ComparableResources") -> None:
        self.cpu -= other.cpu
        self.memory_mb -= other.memory_mb
        self.disk_mb -= other.disk_mb
        self.network_mbits -= other.network_mbits

    def superset(self, other: "ComparableResources") -> Tuple[bool, str]:
        if self.cpu < other.cpu:
            return False, "cpu"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""


# ---------------------------------------------------------------------------
# Constraints / affinities / spread
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Constraint:
    """(reference structs.go Constraint:7669)"""

    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass(frozen=True)
class Affinity:
    """Weighted soft constraint, weight in [-100, 100]
    (reference structs.go Affinity:7791)."""

    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="
    weight: int = 50


@dataclass(frozen=True)
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass(frozen=True)
class Spread:
    """(reference structs.go Spread:7879)"""

    attribute: str = ""
    weight: int = 50
    targets: Tuple[SpreadTarget, ...] = ()


@dataclass
class PolicySpec:
    """Placement-policy weights riding the job: a Gavel-style
    throughput-by-node-class table (normalized to its max and folded
    into the score mean for every candidate) and a migration-cost
    coefficient (a reschedule penalty on every node EXCEPT those
    currently hosting this TG's live allocs — the incumbent's score
    mean is untouched, movers are dragged down — so drains and mass
    replans avoid unnecessary migrations).  Assembled into
    per-(TG, node) weight tensors by sched/policy.py and fused into
    the score kernel."""

    # node-class -> relative throughput (any positive scale; the
    # assembler normalizes by the table max).  Empty = no
    # heterogeneity term.
    throughput: Dict[str, float] = field(default_factory=dict)
    throughput_coefficient: float = 1.0
    # > 0 enables the migration-cost penalty term
    migration_coefficient: float = 0.0
    # only allocs running at least this long mark their node sticky
    # ("penalize moving LONG-RUNNING allocs"); 0 = all live allocs
    min_runtime_s: float = 0.0

    def active(self) -> bool:
        return bool(self.throughput) or self.migration_coefficient != 0.0


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class DrainStrategy:
    deadline_ns: int = 0
    ignore_system_jobs: bool = False
    force_deadline_unix: float = 0.0


@dataclass
class HostVolumeInfo:
    path: str = ""
    read_only: bool = False


@dataclass
class NodeEvent:
    """An entry in a node's event history (reference structs.go
    NodeEvent; emitted via UpsertNodeEventsType, fsm.go:247)."""

    message: str = ""
    subsystem: str = "Cluster"
    details: Dict[str, str] = field(default_factory=dict)
    timestamp: float = field(default_factory=time.time)
    create_index: int = 0


# retained events per node (reference structs.go maxNodeEvents = 10)
MAX_NODE_EVENTS = 10


@dataclass
class Node:
    """(reference structs.go Node:1720)"""

    id: str = field(default_factory=new_id)
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved_resources: NodeReservedResources = field(
        default_factory=NodeReservedResources
    )
    # driver name -> healthy
    drivers: Dict[str, bool] = field(default_factory=dict)
    host_volumes: Dict[str, HostVolumeInfo] = field(default_factory=dict)
    # CSI plugin id -> healthy (node-stage plugins)
    csi_node_plugins: Dict[str, bool] = field(default_factory=dict)
    status: str = NODE_STATUS_INIT
    scheduling_eligibility: str = NODE_SCHED_ELIGIBLE
    drain: bool = False
    drain_strategy: Optional[DrainStrategy] = None
    computed_class: str = ""
    status_updated_at: float = 0.0
    events: List[NodeEvent] = field(default_factory=list)
    create_index: int = 0
    modify_index: int = 0

    def add_event(self, event: "NodeEvent") -> None:
        """Append to the bounded event history (reference
        state_store.go appendNodeEvents caps at maxNodeEvents)."""
        self.events.append(event)
        if len(self.events) > MAX_NODE_EVENTS:
            # the first (registration) event is always retained
            del self.events[1:len(self.events) - MAX_NODE_EVENTS + 1]

    def ready(self) -> bool:
        """(reference structs.go Node.Ready)"""
        return (
            self.status == NODE_STATUS_READY
            and not self.drain
            and self.scheduling_eligibility == NODE_SCHED_ELIGIBLE
        )

    def comparable_resources(self) -> ComparableResources:
        r = self.node_resources
        return ComparableResources(
            cpu=r.cpu, memory_mb=r.memory_mb, disk_mb=r.disk_mb
        )

    def comparable_reserved_resources(self) -> ComparableResources:
        r = self.reserved_resources
        return ComparableResources(
            cpu=r.cpu, memory_mb=r.memory_mb, disk_mb=r.disk_mb
        )

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN


# ---------------------------------------------------------------------------
# Job / TaskGroup / Task
# ---------------------------------------------------------------------------


@dataclass
class RestartPolicy:
    attempts: int = 2
    interval_s: float = 1800.0
    delay_s: float = 15.0
    mode: str = "fail"  # fail | delay


@dataclass
class ReschedulePolicy:
    """(reference structs.go ReschedulePolicy:4144)"""

    attempts: int = 0
    interval_s: float = 0.0
    delay_s: float = 30.0
    delay_function: str = "exponential"  # constant | exponential | fibonacci
    max_delay_s: float = 3600.0
    unlimited: bool = True


@dataclass
class MigrateStrategy:
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0


@dataclass
class UpdateStrategy:
    """Rolling-update / deployment config
    (reference structs.go UpdateStrategy:4245)."""

    stagger_s: float = 30.0
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0
    progress_deadline_s: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def is_empty(self) -> bool:
        return self.max_parallel == 0


@dataclass
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False


@dataclass
class VolumeRequest:
    name: str = ""
    type: str = "host"  # host | csi
    source: str = ""
    read_only: bool = False


# CSI access/attachment modes (reference nomad/structs/csi.go)
CSI_ACCESS_SINGLE_NODE_READER = "single-node-reader-only"
CSI_ACCESS_SINGLE_NODE_WRITER = "single-node-writer"
CSI_ACCESS_MULTI_NODE_READER = "multi-node-reader-only"
CSI_ACCESS_MULTI_NODE_SINGLE_WRITER = "multi-node-single-writer"
CSI_ACCESS_MULTI_NODE_MULTI_WRITER = "multi-node-multi-writer"

CSI_ATTACHMENT_FILE_SYSTEM = "file-system"
CSI_ATTACHMENT_BLOCK_DEVICE = "block-device"

_CSI_SINGLE_NODE_MODES = (
    CSI_ACCESS_SINGLE_NODE_READER,
    CSI_ACCESS_SINGLE_NODE_WRITER,
)


@dataclass
class CSIVolume:
    """An externally-provisioned volume managed by a CSI plugin
    (reference nomad/structs/csi.go CSIVolume; state table
    nomad/state/schema.go csi_volumes).  Claims map alloc id -> node id
    so the watcher can release claims as allocs die."""

    id: str = ""
    namespace: str = DEFAULT_NAMESPACE
    name: str = ""
    external_id: str = ""
    plugin_id: str = ""
    access_mode: str = CSI_ACCESS_SINGLE_NODE_WRITER
    attachment_mode: str = CSI_ATTACHMENT_FILE_SYSTEM
    read_claims: Dict[str, str] = field(default_factory=dict)
    write_claims: Dict[str, str] = field(default_factory=dict)
    schedulable: bool = True
    secrets: Dict[str, str] = field(default_factory=dict)
    parameters: Dict[str, str] = field(default_factory=dict)
    context: Dict[str, str] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def write_free(self) -> bool:
        """Can another writer claim this volume?
        (reference csi.go WriteFreeClaims)"""
        if self.access_mode in (
            CSI_ACCESS_SINGLE_NODE_READER,
            CSI_ACCESS_MULTI_NODE_READER,
        ):
            return False
        if self.access_mode == CSI_ACCESS_MULTI_NODE_MULTI_WRITER:
            return True
        return len(self.write_claims) == 0

    def claimable(self, read_only: bool) -> bool:
        if not self.schedulable:
            return False
        if read_only:
            # single-node modes serialize on one node; modeled as one
            # outstanding claim set like the reference's ReadFreeClaims
            if self.access_mode in _CSI_SINGLE_NODE_MODES:
                return not self.write_claims
            return True
        return self.write_free()

    def claim(self, alloc_id: str, node_id: str, read_only: bool) -> None:
        if read_only:
            self.read_claims[alloc_id] = node_id
        else:
            self.write_claims[alloc_id] = node_id

    def release(self, alloc_id: str) -> bool:
        hit = False
        if self.read_claims.pop(alloc_id, None) is not None:
            hit = True
        if self.write_claims.pop(alloc_id, None) is not None:
            hit = True
        return hit

    def in_use(self) -> bool:
        return bool(self.read_claims or self.write_claims)


@dataclass
class CSIPlugin:
    """Aggregated plugin health view, derived from node fingerprints
    (reference nomad/structs/csi.go CSIPlugin; the reference keeps a
    csi_plugins table, here it is computed from the node table)."""

    id: str = ""
    nodes_healthy: int = 0
    nodes_expected: int = 0
    node_ids: List[str] = field(default_factory=list)


@dataclass
class Lifecycle:
    hook: str = ""  # prestart | poststart | poststop
    sidecar: bool = False


@dataclass
class ConnectUpstream:
    """(reference structs.go ConsulUpstream)"""

    destination_name: str = ""
    local_bind_port: int = 0


@dataclass
class ConsulConnect:
    """Service-mesh stanza (reference structs.go ConsulConnect:
    sidecar_service + proxy upstreams; native mode skips the proxy)."""

    native: bool = False
    sidecar_service: bool = False
    upstreams: List[ConnectUpstream] = field(default_factory=list)


@dataclass
class Service:
    name: str = ""
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    checks: List[Dict[str, Any]] = field(default_factory=list)
    connect: Optional[ConsulConnect] = None


@dataclass
class Task:
    """(reference structs.go Task:6152)"""

    name: str = ""
    driver: str = "exec"
    config: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    services: List[Service] = field(default_factory=list)
    lifecycle: Optional[Lifecycle] = None
    leader: bool = False
    kill_timeout_s: float = 5.0
    artifacts: List[Dict[str, Any]] = field(default_factory=list)
    templates: List[Dict[str, Any]] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)
    dispatch_payload_file: str = ""
    # LogConfig (reference structs.go LogConfig: MaxFiles,
    # MaxFileSizeMB; consumed by client/logmon)
    log_max_files: int = 10
    log_max_file_size_mb: int = 10


SCALING_POLICY_TYPE_HORIZONTAL = "horizontal"

# how many scaling events are retained per task group
# (reference structs.go JobTrackedScalingEvents)
JOB_TRACKED_SCALING_EVENTS = 20


@dataclass
class Namespace:
    """A job namespace (reference nomad/structs Namespace — OSS'd in
    1.0; the 0.13 CLI already ships the command family).  Jobs, CSI
    volumes, and ACL capabilities scope to one."""

    name: str = "default"
    description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def validate(self) -> None:
        import re as _re

        if not _re.fullmatch(r"[a-zA-Z0-9-]{1,128}", self.name):
            raise ValueError(
                "invalid namespace name (alphanumeric + dashes, "
                "max 128 chars)"
            )


@dataclass
class ScalingPolicy:
    """Autoscaling bounds + opaque autoscaler policy attached to a task
    group (reference structs.go ScalingPolicy / scaling stanza;
    state table `scaling_policy`, nomad/state/schema.go:795)."""

    id: str = field(default_factory=new_id)
    type: str = SCALING_POLICY_TYPE_HORIZONTAL
    target: Dict[str, str] = field(default_factory=dict)
    min: int = 1
    max: int = 0
    policy: Dict[str, Any] = field(default_factory=dict)
    enabled: bool = True
    create_index: int = 0
    modify_index: int = 0

    def target_tuple(self) -> Tuple[str, str, str]:
        return (
            self.target.get("Namespace", ""),
            self.target.get("Job", ""),
            self.target.get("Group", ""),
        )

    def canonicalize_for(self, job: "Job", group: str) -> None:
        """Stamp the policy's target from its owning job/group
        (reference structs.go ScalingPolicy.TargetTaskGroup)."""
        self.target = {
            "Namespace": job.namespace,
            "Job": job.id,
            "Group": group,
        }


@dataclass
class ScalingEvent:
    """One scaling action or autoscaler status report
    (reference structs.go ScalingEvent)."""

    time: float = field(default_factory=time.time)
    count: Optional[int] = None
    previous_count: int = 0
    message: str = ""
    error: bool = False
    eval_id: Optional[str] = None
    meta: Dict[str, Any] = field(default_factory=dict)
    create_index: int = 0


@dataclass
class TaskGroup:
    """(reference structs.go TaskGroup:5495)"""

    name: str = ""
    count: int = 1
    tasks: List[Task] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    networks: List[NetworkResource] = field(default_factory=list)
    volumes: Dict[str, VolumeRequest] = field(default_factory=dict)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    migrate: Optional[MigrateStrategy] = None
    update: Optional[UpdateStrategy] = None
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    meta: Dict[str, str] = field(default_factory=dict)
    stop_after_client_disconnect_s: Optional[float] = None
    scaling: Optional[ScalingPolicy] = None


@dataclass
class Periodic:
    enabled: bool = True
    spec: str = ""  # cron spec
    prohibit_overlap: bool = False
    timezone: str = "UTC"


@dataclass
class MultiregionStrategy:
    """(reference structs.go MultiregionStrategy:4645)"""

    max_parallel: int = 0
    on_failure: str = ""  # "", fail_all, fail_local


@dataclass
class MultiregionRegion:
    """(reference structs.go MultiregionRegion:4650)"""

    name: str = ""
    count: int = 0
    datacenters: List[str] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)


@dataclass
class Multiregion:
    """Multi-region deployment spec (reference structs.go
    Multiregion:4597; the OSS deployment watcher carries the spec and
    runs the region-local rollout — cross-region coordination hooks
    live in deploymentwatcher/multiregion_oss.go and are no-ops)."""

    strategy: MultiregionStrategy = field(
        default_factory=MultiregionStrategy
    )
    regions: List[MultiregionRegion] = field(default_factory=list)

    def region(self, name: str) -> Optional[MultiregionRegion]:
        for r in self.regions:
            if r.name == name:
                return r
        return None


@dataclass
class Job:
    """(reference structs.go Job:3748)"""

    id: str = ""
    name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    region: str = DEFAULT_REGION
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    datacenters: List[str] = field(default_factory=lambda: ["dc1"])
    task_groups: List[TaskGroup] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    periodic: Optional[Periodic] = None
    multiregion: Optional[Multiregion] = None
    parameterized: Optional[Dict[str, Any]] = None
    # dispatch input blob (reference structs.go Job.Payload, written to
    # tasks via DispatchPayloadConfig at structs.go DispatchPayload)
    payload: bytes = b""
    parent_id: str = ""
    all_at_once: bool = False
    update: Optional[UpdateStrategy] = None
    # placement-policy weights (heterogeneity throughput + migration
    # cost) consumed by the score kernel; None = policy-less
    policy: Optional[PolicySpec] = None
    meta: Dict[str, str] = field(default_factory=dict)
    stop: bool = False
    status: str = JOB_STATUS_PENDING
    version: int = 0
    stable: bool = False
    submit_time: float = field(default_factory=time.time)
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0

    def namespaced_id(self) -> Tuple[str, str]:
        return (self.namespace, self.id)

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def stopped(self) -> bool:
        return self.stop

    def is_periodic(self) -> bool:
        return self.periodic is not None

    def is_parameterized(self) -> bool:
        return self.parameterized is not None

    def required_signals(self) -> Dict[str, Dict[str, List[str]]]:
        return {}


# ---------------------------------------------------------------------------
# Allocation
# ---------------------------------------------------------------------------


@dataclass
class RescheduleEvent:
    reschedule_time: float = 0.0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_s: float = 0.0


@dataclass
class RescheduleTracker:
    events: List[RescheduleEvent] = field(default_factory=list)


@dataclass
class DesiredTransition:
    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass
class TaskState:
    state: str = "pending"  # pending | running | dead
    failed: bool = False
    restarts: int = 0
    started_at: float = 0.0
    finished_at: float = 0.0
    events: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp: float = 0.0
    canary: bool = False

    def is_healthy(self) -> bool:
        return self.healthy is True

    def is_unhealthy(self) -> bool:
        return self.healthy is False


@dataclass
class Allocation:
    """(reference structs.go Allocation:8519)"""

    id: str = field(default_factory=new_id)
    namespace: str = DEFAULT_NAMESPACE
    eval_id: str = ""
    name: str = ""  # "<job>.<group>[<index>]"
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    allocated_resources: Optional[AllocatedResources] = None
    desired_status: str = ALLOC_DESIRED_RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = ALLOC_CLIENT_STATUS_PENDING
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    reschedule_tracker: Optional[RescheduleTracker] = None
    previous_allocation: str = ""
    next_allocation: str = ""
    followup_eval_id: str = ""
    preempted_by_allocation: str = ""
    metrics: Optional["AllocMetric"] = None
    create_time: float = field(default_factory=time.time)
    modify_time: float = field(default_factory=time.time)
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0

    def terminal_status(self) -> bool:
        """Terminal by desired or client state
        (reference structs.go Allocation.TerminalStatus)."""
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return True
        return self.client_terminal_status()

    def client_terminal_status(self) -> bool:
        return self.client_status in (
            ALLOC_CLIENT_STATUS_COMPLETE,
            ALLOC_CLIENT_STATUS_FAILED,
            ALLOC_CLIENT_STATUS_LOST,
        )

    def comparable_resources(self) -> ComparableResources:
        if self.allocated_resources is None:
            return ComparableResources()
        return self.allocated_resources.comparable()

    def index(self) -> int:
        """Parse the instance index out of the alloc name."""
        l = self.name.rfind("[")
        r = self.name.rfind("]")
        if l == -1 or r == -1 or r < l:
            return -1
        return int(self.name[l + 1 : r])

    def job_namespaced_id(self) -> Tuple[str, str]:
        return (self.namespace, self.job_id)

    def ran_successfully(self) -> bool:
        return self.client_status == ALLOC_CLIENT_STATUS_COMPLETE

    def migrate_status(self) -> bool:
        return self.desired_transition.should_migrate()

    # -- rescheduling (reference structs.go Allocation.NextRescheduleTime,
    #    NextDelay, RescheduleEligible) --------------------------------------

    def reschedule_policy(self) -> Optional["ReschedulePolicy"]:
        if self.job is None:
            return None
        tg = self.job.lookup_task_group(self.task_group)
        if tg is None:
            return None
        return tg.reschedule_policy

    def last_event_time(self) -> float:
        last = 0.0
        for state in self.task_states.values():
            if state.finished_at > last:
                last = state.finished_at
        return last or self.modify_time

    def next_delay(self) -> float:
        """Delay before the next reschedule attempt, per the policy's delay
        function (constant | exponential | fibonacci), capped at max_delay
        (reference structs.go ReschedulePolicy/NextDelay)."""
        policy = self.reschedule_policy()
        if policy is None:
            return 0.0
        delay = policy.delay_s
        tracker = self.reschedule_tracker
        n_prev = len(tracker.events) if tracker else 0
        if policy.delay_function == "exponential":
            delay = policy.delay_s * (2**n_prev)
        elif policy.delay_function == "fibonacci":
            a, b = 0.0, policy.delay_s
            for _ in range(n_prev):
                a, b = b, a + b
            delay = b
        if policy.max_delay_s > 0:
            delay = min(delay, policy.max_delay_s)
        return delay

    def next_reschedule_time(self) -> Tuple[float, bool]:
        """Returns (reschedule_time, eligible)."""
        policy = self.reschedule_policy()
        fail_time = self.last_event_time()
        if (
            self.desired_status == ALLOC_DESIRED_STOP
            or self.client_status != ALLOC_CLIENT_STATUS_FAILED
            or fail_time == 0.0
            or policy is None
        ):
            return 0.0, False
        if policy.attempts == 0 and not policy.unlimited:
            return 0.0, False
        next_time = fail_time + self.next_delay()
        eligible = policy.unlimited or (
            policy.attempts > 0 and self.reschedule_tracker is None
        )
        if (
            policy.attempts > 0
            and self.reschedule_tracker is not None
            and self.reschedule_tracker.events
        ):
            attempted = 0
            for event in reversed(self.reschedule_tracker.events):
                if fail_time - event.reschedule_time < policy.interval_s:
                    attempted += 1
            eligible = attempted < policy.attempts
        return next_time, eligible

    def should_client_stop(self) -> bool:
        if self.job is None:
            return False
        tg = self.job.lookup_task_group(self.task_group)
        return (
            tg is not None
            and tg.stop_after_client_disconnect_s is not None
        )

    def wait_client_stop(self) -> float:
        tg = (
            self.job.lookup_task_group(self.task_group)
            if self.job is not None
            else None
        )
        timeout = (
            tg.stop_after_client_disconnect_s
            if tg is not None and tg.stop_after_client_disconnect_s
            else 0.0
        )
        return self.last_event_time() + timeout


def alloc_name(job_id: str, group: str, idx: int) -> str:
    return f"{job_id}.{group}[{idx}]"


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


@dataclass
class Evaluation:
    """(reference structs.go Evaluation:9512)"""

    id: str = field(default_factory=new_id)
    namespace: str = DEFAULT_NAMESPACE
    priority: int = JOB_DEFAULT_PRIORITY
    type: str = JOB_TYPE_SERVICE  # scheduler type
    triggered_by: str = EVAL_TRIGGER_JOB_REGISTER
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait_until: float = 0.0
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    failed_tg_allocs: Dict[str, "AllocMetric"] = field(default_factory=dict)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    annotate_plan: bool = False
    # storm-family override for the eval broker's job_family(): the
    # heartbeat sweeper stamps every replan eval of one mass
    # node-death wave with the wave's hint so evals across unrelated
    # jobs coalesce into ONE storm solve; "" = derive from job_id
    family_hint: str = ""
    queued_allocations: Dict[str, int] = field(default_factory=dict)
    leader_ack: str = ""
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    modify_time: float = 0.0

    def terminal_status(self) -> bool:
        return self.status in (
            EVAL_STATUS_COMPLETE,
            EVAL_STATUS_FAILED,
            EVAL_STATUS_CANCELLED,
        )

    def should_enqueue(self) -> bool:
        return self.status == EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        return self.status == EVAL_STATUS_BLOCKED

    def make_plan(self, job: Optional[Job]) -> "Plan":
        return Plan(
            eval_id=self.id,
            priority=self.priority,
            job=job,
        )

    def next_rolling_eval(self, wait_s: float) -> "Evaluation":
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_until=time.time() + wait_s,
            previous_eval=self.id,
        )

    def create_blocked_eval(
        self,
        class_eligibility: Dict[str, bool],
        escaped: bool,
        quota_reached: str,
    ) -> "Evaluation":
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_QUEUED_ALLOCS,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=dict(class_eligibility),
            escaped_computed_class=escaped,
            quota_limit_reached=quota_reached,
        )

    def create_failed_follow_up_eval(self, wait_s: float) -> "Evaluation":
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_FAILED_FOLLOW_UP,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_until=time.time() + wait_s,
            previous_eval=self.id,
        )


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------


@dataclass
class Plan:
    """The scheduler's proposed state mutation
    (reference structs.go Plan:9805)."""

    eval_id: str = ""
    eval_token: str = ""
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    job: Optional[Job] = None
    # node id -> allocs to stop/evict on that node
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node id -> new/updated allocs on that node
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node id -> allocs preempted on that node
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional["Deployment"] = None
    # deployment id -> status update
    deployment_updates: List["DeploymentStatusUpdate"] = field(default_factory=list)
    annotations: Optional[Dict[str, Any]] = None
    snapshot_index: int = 0
    # leadership generation the producing wave/chain captured when it
    # STARTED (not when the plan reaches the store): the replicated
    # FSM fence compares this against the committed leadership
    # barrier, so a straggler wave from a deposed generation is
    # rejected even if its server has since been re-elected
    leader_gen: Optional[int] = None

    def append_stopped_alloc(
        self, alloc: Allocation, desired_desc: str, client_status: str = ""
    ) -> None:
        """(reference structs.go Plan.AppendStoppedAlloc)"""
        new_alloc = replace(alloc)
        new_alloc.desired_status = ALLOC_DESIRED_STOP
        new_alloc.desired_description = desired_desc
        if client_status:
            new_alloc.client_status = client_status
        self.node_update.setdefault(alloc.node_id, []).append(new_alloc)

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_preempted_alloc(
        self, alloc: Allocation, preempting_alloc_id: str
    ) -> None:
        new_alloc = replace(alloc)
        new_alloc.desired_status = ALLOC_DESIRED_EVICT
        new_alloc.preempted_by_allocation = preempting_alloc_id
        new_alloc.desired_description = (
            f"Preempted by alloc ID {preempting_alloc_id}"
        )
        self.node_preemptions.setdefault(alloc.node_id, []).append(new_alloc)

    def is_no_op(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and self.deployment is None
            and not self.deployment_updates
        )


@dataclass
class AllocationDiff:
    """Minimal wire form of a stopped/preempted allocation: just the
    fields the FSM needs to apply the stop against its local copy
    (reference structs.go AllocationDiff + Plan.NormalizeAllocations,
    nomad/plan_apply.go:324-344 — stops/evictions replicate as diffs,
    not full Job-bearing alloc structs)."""

    id: str = ""
    desired_status: str = ""
    desired_description: str = ""
    client_status: str = ""
    followup_eval_id: str = ""
    preempted_by_allocation: str = ""


@dataclass
class PlanResult:
    """(reference structs.go PlanResult:9988)"""

    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional["Deployment"] = None
    deployment_updates: List["DeploymentStatusUpdate"] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0
    # True when node_update/node_preemptions hold AllocationDiffs that
    # must be denormalized against state before applying
    normalized: bool = False

    def is_full_commit(self, plan: Plan) -> bool:
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual

    def full_commit(self, plan: Plan) -> Tuple[bool, int, int]:
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual


# ---------------------------------------------------------------------------
# Deployment
# ---------------------------------------------------------------------------


@dataclass
class DeploymentState:
    """Per-task-group deployment state
    (reference structs.go DeploymentState)."""

    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: List[str] = field(default_factory=list)
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_s: float = 0.0
    require_progress_by: float = 0.0


@dataclass
class Deployment:
    """(reference structs.go Deployment:8178)"""

    id: str = field(default_factory=new_id)
    namespace: str = DEFAULT_NAMESPACE
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_create_index: int = 0
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = "Deployment is running"
    create_index: int = 0
    modify_index: int = 0

    def active(self) -> bool:
        return self.status in (DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED)

    def requires_promotion(self) -> bool:
        return any(
            s.desired_canaries > 0 and not s.promoted
            for s in self.task_groups.values()
        )

    def has_auto_promote(self) -> bool:
        return all(s.auto_promote for s in self.task_groups.values()) and bool(
            self.task_groups
        )


@dataclass
class DeploymentStatusUpdate:
    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


@dataclass
class DesiredUpdates:
    """Per-task-group planned change counts, surfaced in `job plan`
    (reference structs.go DesiredUpdates)."""

    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


# ---------------------------------------------------------------------------
# Placement metrics (reference structs.go AllocMetric:9184)
# ---------------------------------------------------------------------------


@dataclass
class NodeScoreMeta:
    node_id: str = ""
    scores: Dict[str, float] = field(default_factory=dict)
    norm_score: float = 0.0


@dataclass
class AllocMetric:
    # monotone per-eval select sequence (EvalContext.reset stamps it):
    # lets consumers pick the freshest placement's metric for a task
    # group without relying on plan-collection iteration order
    seq: int = 0
    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)  # dc -> count
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    quota_exhausted: List[str] = field(default_factory=list)
    scores: Dict[str, float] = field(default_factory=dict)
    score_meta: List[NodeScoreMeta] = field(default_factory=list)
    allocation_time_s: float = 0.0
    coalesced_failures: int = 0

    def evaluate_node(self) -> None:
        self.nodes_evaluated += 1

    def filter_node(self, node: Optional[Node], constraint: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = (
                self.class_filtered.get(node.node_class, 0) + 1
            )
        if constraint:
            self.constraint_filtered[constraint] = (
                self.constraint_filtered.get(constraint, 0) + 1
            )

    def exhausted_node(self, node: Optional[Node], dimension: str) -> None:
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = (
                self.class_exhausted.get(node.node_class, 0) + 1
            )
        if dimension:
            self.dimension_exhausted[dimension] = (
                self.dimension_exhausted.get(dimension, 0) + 1
            )

    # ScoreMetaData entries kept on any read/serialization surface
    # (reference lib/kheap k=5)
    SCORE_META_TOP_K = 5

    def score_node(self, node: Node, name: str, score: float) -> None:
        # Top-K score metadata kept simple: record everything, trim on
        # read via top_score_meta (reference uses lib/kheap with k=5).
        for meta in self.score_meta:
            if meta.node_id == node.id:
                meta.scores[name] = score
                if name == "normalized-score":
                    meta.norm_score = score
                return
        meta = NodeScoreMeta(node_id=node.id, scores={name: score})
        if name == "normalized-score":
            meta.norm_score = score
        self.score_meta.append(meta)

    def max_normalized_score(self) -> float:
        if not self.score_meta:
            return 0.0
        return max(m.norm_score for m in self.score_meta)

    def node_norm_score(self, node_id: str) -> float:
        for meta in self.score_meta:
            if meta.node_id == node_id:
                return meta.norm_score
        return 0.0

    def top_score_meta(
        self, k: int = SCORE_META_TOP_K, winner_node_id: str = ""
    ) -> List["NodeScoreMeta"]:
        """The trim-on-read the score_node docstring promises: top-K
        entries by norm_score (stable: earlier-scored wins ties), with
        the actual winner always retained even when its normalized
        score was not among the K best (preemption splices and walk
        emission order can crown a non-maximal node).  The in-memory
        list stays complete; every serialization surface reads through
        here so score_meta can't ship 1k entries per eval."""
        if len(self.score_meta) <= k:
            return list(self.score_meta)
        ranked = sorted(
            range(len(self.score_meta)),
            key=lambda i: (-self.score_meta[i].norm_score, i),
        )
        keep = set(ranked[:k])
        if winner_node_id:
            for i, meta in enumerate(self.score_meta):
                if meta.node_id == winner_node_id and i not in keep:
                    # the winner displaces the weakest kept entry
                    keep.discard(ranked[k - 1])
                    keep.add(i)
                    break
        return [
            m for i, m in enumerate(self.score_meta) if i in keep
        ]


# ---------------------------------------------------------------------------
# Scheduler configuration (reference structs.go SchedulerConfiguration)
# ---------------------------------------------------------------------------


@dataclass
class PreemptionConfig:
    system_scheduler_enabled: bool = True
    batch_scheduler_enabled: bool = False
    service_scheduler_enabled: bool = False


@dataclass
class SchedulerConfiguration:
    scheduler_algorithm: str = SCHEDULER_ALGORITHM_BINPACK
    preemption_config: PreemptionConfig = field(default_factory=PreemptionConfig)
    # nomad-tpu extension: route service/batch/system evals through the
    # vectorized TPU scoring backend (SURVEY.md section 7.6 analog of the
    # reference's runtime-mutable scheduler config, stack.go:256,382).
    tpu_scheduler_enabled: bool = False

    def effective_scheduler_algorithm(self) -> str:
        return self.scheduler_algorithm or SCHEDULER_ALGORITHM_BINPACK
