"""Adaptive-decision ledger: flight data for the control loops.

The stack is steered by a web of EWMA heuristics — chunk-width
planning, the adaptive gulp cap, admission gating, storm triggers and
settle beats, the overload mode ladder, fan-out lease sizing,
watchdog budgets, federation retry-region selection.  Each of those
sites picks an action from alternatives using a snapshot of signals,
and until this module none of them recorded *why*.  The ledger is a
process-wide bounded ring of structured :class:`DecisionRecord` dicts
(site slug, inputs snapshot, chosen action, alternatives considered,
outcome, trace-id link) so an operator — or the future self-tuning
controller (ROADMAP item 6) — can join "what the system did" to "what
it saw when it did it".

Every site MUST be declared in :data:`DECISION_SITES` (slug →
nomadlint path key); the ``decision-ledger`` lint rule statically
checks both directions: a registered slug must be recorded by its
owning module, and a ``record("slug", ...)`` call site must be
registered.  Per-site counters (``decision.site.<slug>``) make the
coverage observable at runtime too — absence of a series must mean
"site never fired", not "not exported", so Server zero-registers
:data:`DECISION_COUNTERS` / :data:`DECISION_GAUGES` at construction.

``NOMAD_TPU_DECISIONS=0`` opts out: ``record()`` returns before
touching the ring or any metric, and hot paths additionally gate on
``DECISIONS.enabled`` so they skip building the inputs dict at all.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "DECISIONS",
    "DECISION_COUNTERS",
    "DECISION_GAUGES",
    "DECISION_SITES",
    "DecisionLedger",
    "decisions_enabled",
    "decisions_ring",
]

# Registry of every adaptive decision site: slug -> the nomadlint
# DEFAULT_PATHS key of the module that owns (records) it.  The
# decision-ledger rule parses this literal dict, so it must stay a
# plain literal — no comprehensions, no computed keys.
DECISION_SITES: Dict[str, str] = {
    "chunk_width": "batch_worker",
    "adaptive_cap": "batch_worker",
    "admission_defer": "batch_worker",
    "storm_trigger": "batch_worker",
    "storm_settle": "batch_worker",
    "overload_mode": "overload",
    "fanout_lease": "fanout",
    "fanout_nack": "fanout",
    "watchdog_budget": "device_supervisor",
    "federation_retry": "federation",
}

# Literal tuples (the metric-family lint reads them via
# ast-literal extraction — keep them spelled out, one name per site).
DECISION_COUNTERS = (
    "decision.recorded",
    "decision.evicted",
    "decision.site.chunk_width",
    "decision.site.adaptive_cap",
    "decision.site.admission_defer",
    "decision.site.storm_trigger",
    "decision.site.storm_settle",
    "decision.site.overload_mode",
    "decision.site.fanout_lease",
    "decision.site.fanout_nack",
    "decision.site.watchdog_budget",
    "decision.site.federation_retry",
)
DECISION_GAUGES = ("decision.ring_depth",)


def decisions_enabled() -> bool:
    return os.environ.get("NOMAD_TPU_DECISIONS", "1") != "0"


def decisions_ring() -> int:
    try:
        return max(
            16, int(os.environ.get("NOMAD_TPU_DECISIONS_RING", "512"))
        )
    except ValueError:
        return 512


class DecisionLedger:
    """Process-wide bounded ring of adaptive-decision records.

    Like ``TRACE`` this is a module singleton shared by every Server
    in the process (TestCluster servers report the same ledger; the
    cluster fan-in dedups by ``seq``).  All mutation happens under
    ``_lock``; reads snapshot under the same lock and return copies,
    so callers can serialize without racing writers.
    """

    def __init__(self, ring: Optional[int] = None) -> None:
        self.enabled = decisions_enabled()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring or decisions_ring())
        self._seq = 0
        self._evicted = 0
        from .tsan import maybe_instrument

        maybe_instrument(self, "DecisionLedger")

    # -- write path ---------------------------------------------------

    def record(
        self,
        site: str,
        action: str,
        *,
        inputs: Optional[Dict[str, Any]] = None,
        alternatives: Optional[List[Any]] = None,
        outcome: str = "applied",
        trace_id: str = "",
        metrics=None,
    ) -> Optional[Dict[str, Any]]:
        """Append one record; returns it, or None when opted out.

        ``metrics`` is the calling server's Metrics handle — passed
        per call because the ledger is process-wide but counters are
        per-server.  Cheap by design: one dict build + a lock'd
        append; hot paths should still gate on ``.enabled`` to skip
        assembling ``inputs``.
        """
        if not self.enabled:
            return None
        rec: Dict[str, Any] = {
            "seq": 0,  # assigned under the lock below
            "t": time.time(),
            "site": site,
            "action": action,
            "inputs": dict(inputs or {}),
            "alternatives": list(alternatives or ()),
            "outcome": outcome,
            "trace_id": trace_id or "",
        }
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            evicting = len(self._ring) == self._ring.maxlen
            if evicting:
                self._evicted += 1
            self._ring.append(rec)
            depth = len(self._ring)
        if metrics is not None:
            metrics.incr("decision.recorded")
            if site in DECISION_SITES:
                metrics.incr("decision.site." + site)
            if evicting:
                metrics.incr("decision.evicted")
            metrics.set_gauge("decision.ring_depth", depth)
        return rec

    # -- read path ----------------------------------------------------

    def recent(
        self,
        site: Optional[str] = None,
        outcome: Optional[str] = None,
        trace: Optional[str] = None,
        limit: int = 64,
    ) -> List[Dict[str, Any]]:
        """Newest-first records, optionally filtered."""
        with self._lock:
            records = list(self._ring)
        out: List[Dict[str, Any]] = []
        for rec in reversed(records):
            if site and rec["site"] != site:
                continue
            if outcome and rec["outcome"] != outcome:
                continue
            if trace and rec["trace_id"] != trace:
                continue
            out.append(dict(rec))
            if len(out) >= limit:
                break
        return out

    def counts(self) -> Dict[str, int]:
        """Per-site record counts currently retained in the ring."""
        with self._lock:
            records = list(self._ring)
        by_site: Dict[str, int] = {}
        for rec in records:
            by_site[rec["site"]] = by_site.get(rec["site"], 0) + 1
        return by_site

    def to_dict(
        self,
        site: Optional[str] = None,
        outcome: Optional[str] = None,
        trace: Optional[str] = None,
        limit: int = 64,
    ) -> Dict[str, Any]:
        with self._lock:
            depth = len(self._ring)
            cap = self._ring.maxlen
            evicted = self._evicted
        return {
            "enabled": self.enabled,
            "ring": {"depth": depth, "cap": cap, "evicted": evicted},
            "sites": sorted(DECISION_SITES),
            "counts": self.counts(),
            "decisions": self.recent(
                site=site, outcome=outcome, trace=trace, limit=limit
            ),
        }

    # -- test / bench hooks -------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._evicted = 0


DECISIONS = DecisionLedger()
