from .mesh import (  # noqa: F401
    make_mesh,
    sharded_batch_plan,
    sharded_score_and_select,
    node_sharding,
    eval_sharding,
)
