"""Pod composition: a live worker HEADS a multi-process mesh.

PR 11's multi-host path assumed lockstep SPMD: every process runs the
same worker over the same inputs in the same order (the dist_smoke
harness drives both ranks synchronously).  A fan-out follower cannot
lockstep — it leases evals from the leader's broker at its own pace,
so no peer process could independently reproduce its launch sequence.

This module makes the follower's worker process the HEAD of its
`jax.distributed` world and streams the launch sequence to the other
world members (PEERS) over an ordered TCP channel:

* the head sends each mesh operation (mirror full/bulk/delta sync,
  chain launch, storm solve) as one framed message, THEN executes it;
* each peer executes messages strictly in receive order.

TCP FIFO delivery makes the collective launch sequences identical by
construction — the multi-controller contract — while everything
non-collective (``mesh_put`` / ``make_array_from_callback`` staging)
stays process-local.  Mirror deltas re-run PR 11's per-host flush
protocol on the peer: the head ships only the SORTED dirty rows and
their three value columns (O(dirty rows) bytes on the wire), and the
peer rebuilds its own shard-local ``[D, w]`` staging from them.

Device-resident operands never cross the wire: the chain's usage
columns come from the peer's own mirror registry ("mirror") or its
own previous launch's carry ("carry"), which track the head's
bit-for-bit because both sides applied the same update stream.

``NOMAD_TPU_POD_PORT`` (head listen port) turns the head side on;
peers run ``python -m nomad_tpu.parallel.pod`` with the same
``NOMAD_TPU_DIST*`` world knobs and a nonzero ``NOMAD_TPU_DIST_ID``.
``NOMAD_TPU_POD_CHECK=1`` makes every chain/storm launch round-trip a
result digest from every peer — the parity gate the bigworld smoke
asserts (head and peers realize identical replicated outputs).
"""
from __future__ import annotations

import argparse
import os
import pickle
import socket
import struct
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

_LEN = struct.Struct(">Q")


def send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_msg(sock: socket.socket):
    head = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(head)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("pod channel closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def pod_check_enabled() -> bool:
    return os.environ.get("NOMAD_TPU_POD_CHECK") == "1"


def result_digest(*arrays) -> str:
    """Order-stable digest of realized (replicated) outputs, shared by
    head and peer for the POD_CHECK parity gate."""
    import hashlib

    h = hashlib.sha256()
    for a in arrays:
        arr = np.ascontiguousarray(np.asarray(a))
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class PodService:
    """Head side: accepts the world's peer connections and broadcasts
    the mesh-operation stream in FIFO order.  All sends serialize
    behind one lock — interleaved messages from two threads would
    diverge the peers' collective order from the head's."""

    def __init__(self, port: int, n_peers: int) -> None:
        self.n_peers = n_peers
        self._srv = socket.socket(
            socket.AF_INET, socket.SOCK_STREAM
        )
        self._srv.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
        )
        self._srv.bind(("127.0.0.1", port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen(max(1, n_peers))
        self._peers: List[socket.socket] = []
        self._lock = threading.Lock()
        self._accept_cond = threading.Condition(self._lock)
        self._closed = False
        self.check = pod_check_enabled()
        t = threading.Thread(
            target=self._accept_loop, name="pod-accept", daemon=True
        )
        t.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            with self._lock:
                self._peers.append(conn)
                self._accept_cond.notify_all()
                if len(self._peers) >= self.n_peers:
                    return

    def wait_peers(self, timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        with self._lock:
            while len(self._peers) < self.n_peers:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"pod head: {len(self._peers)}/"
                        f"{self.n_peers} peers connected"
                    )
                self._accept_cond.wait(remaining)

    def send(self, kind: str, *payload) -> None:
        """Broadcast one operation.  Blocks until the full world is
        connected — executing a collective before every member can
        follow would deadlock the pod at rendezvous."""
        self.wait_peers()
        with self._lock:
            if self._closed:
                raise RuntimeError("pod service closed")
            for sock in self._peers:
                send_msg(sock, (kind,) + payload)

    def check_results(self, digest: str) -> None:
        """POD_CHECK parity gate: collect one digest per peer for the
        launch just executed and require equality with the head's."""
        if not self.check:
            return
        with self._lock:
            for sock in self._peers:
                got = recv_msg(sock)
                if got != ("digest", digest):
                    raise AssertionError(
                        f"pod parity: peer digest {got!r} != head "
                        f"{digest!r}"
                    )

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for sock in self._peers:
                try:
                    send_msg(sock, ("bye",))
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            try:
                self._srv.close()
            except OSError:
                pass


def build_worker_mesh():
    """The worker's mesh bring-up, shared verbatim by head (via
    BatchWorker._make_mesh) and peer: join the NOMAD_TPU_DIST* world,
    then lay every visible device (capped by NOMAD_TPU_MESH_DEVICES)
    along the node axis.  Identical env -> identical mesh on every
    member, which the collective programs require."""
    import jax

    from .mesh import distributed_init, make_mesh

    distributed_init()
    n = len(jax.devices())
    try:
        cap = int(os.environ.get("NOMAD_TPU_MESH_DEVICES", "0"))
    except ValueError:
        cap = 0
    if cap > 0:
        n = min(n, cap)
    if n <= 1:
        return None
    return make_mesh(n_devices=n, eval_axis=1)


class PodPeer:
    """Peer side: one registry of device-resident state (the sharded
    usage mirror and the running chain carry) plus the message loop
    that replays the head's operation stream against it."""

    def __init__(self, mesh) -> None:
        self.mesh = mesh
        self.mirror: Optional[tuple] = None
        self.carry = None
        self._runners: Dict[tuple, object] = {}
        self._storm_fns: Dict[tuple, object] = {}
        self.check = pod_check_enabled()

    # -- registry ops (one per head-side message kind) ------------------

    def mirror_full(self, host_cols) -> None:
        from jax.sharding import PartitionSpec as P

        from .mesh import mesh_put

        self.mirror = tuple(
            mesh_put(self.mesh, col, P("nodes"))
            for col in host_cols
        )

    def mirror_bulk(self, host_used) -> None:
        from jax.sharding import PartitionSpec as P

        from .mesh import mesh_put

        assert self.mirror is not None, "bulk before full sync"
        self.mirror = self.mirror[:3] + tuple(
            mesh_put(self.mesh, col, P("nodes"))
            for col in host_used
        )

    def mirror_delta(self, idx, vals3, capacity) -> None:
        """Replay PR 11's per-host flush: rebuild the shard-local
        [D, w] staging from the (sorted) global dirty rows, gathering
        THIS process's rows from the wire values."""
        from jax.sharding import PartitionSpec as P

        from ..ops.batch import (
            hostlocal_staging,
            patch_rows_hostlocal,
        )
        from .mesh import local_device_positions, mesh_put

        assert self.mirror is not None, "delta before full sync"
        idx = np.asarray(idx, dtype=np.int32)
        idx_stack, per_dev, width = hostlocal_staging(
            self.mesh, idx, capacity
        )
        idx_dev = mesh_put(self.mesh, idx_stack, P("nodes"))
        n_dev = self.mesh.devices.size
        local_pos = local_device_positions(self.mesh)
        patch = patch_rows_hostlocal(self.mesh, donate=False)
        patched = []
        for col, vals in zip(self.mirror[3:], vals3):
            vals = np.asarray(vals)
            vals_stack = np.zeros((n_dev, width), dtype=vals.dtype)
            for d in local_pos:
                sel = per_dev[d]
                # wire values are aligned with the sorted idx; the
                # shard's rows map back via binary search
                pos = np.searchsorted(idx, np.asarray(sel))
                vals_stack[d, : len(sel)] = vals[pos]
            vals_dev = mesh_put(
                self.mesh, vals_stack, P("nodes")
            )
            patched.append(
                patch(col, idx_dev, vals_dev)  # nomadlint: disable=donation-safety -- patch is built with donate=False above; col is read-only here and the mirror slot is rebound right after the loop
            )
        self.mirror = self.mirror[:3] + tuple(patched)

    def chain(self, meta: dict, args_tail: tuple) -> Optional[str]:
        from .mesh import place_chain_inputs, sharded_chained_plan

        assert self.mirror is not None, "chain before mirror sync"
        used = (
            self.carry
            if meta["used"] == "carry"
            else self.mirror[3:6]
        )
        assert used is not None, "carry chain before any chunk"
        key = (
            meta["n_picks"], meta["spread_fit"],
            meta["with_spread"], meta["spread_even"],
        )
        runner = self._runners.get(key)
        if runner is None:
            runner = sharded_chained_plan(
                self.mesh, meta["n_picks"], meta["spread_fit"],
                with_spread=meta["with_spread"],
                spread_even=meta["spread_even"],
                return_carry=True,
            )
            self._runners[key] = runner
        args = self.mirror[:3] + tuple(used) + tuple(args_tail)
        args = place_chain_inputs(
            self.mesh, args,
            with_spread=meta["with_spread"],
            spread_even=meta["spread_even"],
        )
        rows_j, pulls_j, used_out = runner(*args)
        self.carry = used_out
        if self.check:
            return result_digest(rows_j, pulls_j)
        return None

    def storm(
        self, inputs_host, spread_fit: bool, max_rounds: int
    ) -> Optional[str]:
        from ..ops.solve import (
            StormInputs,
            storm_assignment_sharded,
        )
        from ..sched.storm import stage_for_mesh

        assert self.mirror is not None, "storm before mirror sync"
        inputs = StormInputs(*inputs_host)
        weighted = inputs.policy_tput_term is not None
        key = (spread_fit, max_rounds, weighted)
        fn = self._storm_fns.get(key)
        if fn is None:
            fn = storm_assignment_sharded(
                self.mesh, spread_fit=spread_fit,
                max_rounds=max_rounds, weighted=weighted,
            )
            self._storm_fns[key] = fn
        inp = stage_for_mesh(inputs, self.mesh)
        out = fn(inp, self.mirror)
        if self.check:
            return result_digest(*out)
        # realize anyway: an error inside the solve must surface on
        # the peer too, not linger as a poisoned future
        for x in out:
            np.asarray(x)
        return None

    def reset(self) -> None:
        self.mirror = None
        self.carry = None

    # -- message loop ---------------------------------------------------

    def serve(self, sock: socket.socket) -> None:
        while True:
            msg = recv_msg(sock)
            kind = msg[0]
            if kind == "bye":
                return
            digest = None
            if kind == "mirror_full":
                self.mirror_full(msg[1])
            elif kind == "mirror_bulk":
                self.mirror_bulk(msg[1])
            elif kind == "mirror_delta":
                self.mirror_delta(msg[1], msg[2], msg[3])
            elif kind == "chain":
                digest = self.chain(msg[1], msg[2])
            elif kind == "storm":
                digest = self.storm(msg[1], msg[2], msg[3])
            elif kind == "reset":
                self.reset()
            else:
                raise ValueError(f"unknown pod message {kind!r}")
            if digest is not None:
                send_msg(sock, ("digest", digest))


def run_peer(head_port: int, connect_timeout: float = 120.0) -> None:
    """Peer process entrypoint: join the world, build the mesh, dial
    the head and replay its stream until ``bye``."""
    mesh = build_worker_mesh()
    if mesh is None:
        raise RuntimeError(
            "pod peer: no multi-device mesh (check XLA_FLAGS / "
            "NOMAD_TPU_DIST* env)"
        )
    deadline = time.monotonic() + connect_timeout
    sock = None
    while sock is None:
        try:
            sock = socket.create_connection(
                ("127.0.0.1", head_port), timeout=5.0
            )
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    print(f"POD_PEER_READY port={head_port}", flush=True)
    PodPeer(mesh).serve(sock)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="nomad-tpu pod peer (mesh world member)"
    )
    parser.add_argument(
        "--head-port", type=int, required=True,
        help="head worker's NOMAD_TPU_POD_PORT",
    )
    parser.add_argument(
        "--connect-timeout", type=float, default=120.0
    )
    args = parser.parse_args(argv)
    run_peer(args.head_port, args.connect_timeout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
