"""Device mesh + shardings for multi-chip scheduling.

The reference scales by running `NumSchedulers` workers per server and
federating regions over Serf/Raft (SURVEY.md section 2.10); the TPU-native
equivalents are two mesh axes:

* ``evals`` — data parallelism over independent evaluations (the unit the
  reference parallelizes across workers; broker dedup keeps them
  conflict-light, the plan applier serializes the rest);
* ``nodes`` — the long axis: the cluster's node table sharded across
  chips, the honest analog of sequence/context parallelism for a cluster
  scheduler (SURVEY.md section 5 "long-context").

Scoring is embarrassingly parallel along ``nodes``: the only cross-shard
communication is an all-gather of the per-node score/feasibility vectors
(f64 + bool per node — tens of KB at 10k nodes, ICI-cheap) plus O(devices)
walk carries.  In the production chained planner (sharded_chained_plan)
the selection walk itself is ALSO sharded along the permuted axis —
local cumsums with an exchanged per-shard carry (parallel scan), pmin/
pmax winner reductions — so per-device FLOPs genuinely scale ~1/devices
(asserted via compiled cost analysis in tests/test_parallel.py) while
decisions stay bit-identical to the single-chip kernel.
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f=None, **kwargs):
    """shard_map with replication checking off: the selection walk's
    outputs are replicated by construction (post-all-gather), which the
    static varying-axes inference cannot prove."""
    for flag in ("check_vma", "check_rep"):
        try:
            return _shard_map(f, **kwargs, **{flag: False})
        except TypeError:
            continue
    return _shard_map(f, **kwargs)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.batch import BatchInputs, plan_picks
from ..ops.score import ScoreInputs, _limited_walk_argmax, _score_vectors


# -- multi-host distribution (NOMAD_TPU_DIST_*) ------------------------
#
# The same NamedSharding program that shards the node axis across one
# host's chips runs UNCHANGED across processes on a TPU pod: each
# process holds its own slice of every P("nodes") array, the jitted
# shard_map collectives rendezvous over ICI/DCN, and every process
# executes the identical SPMD launch sequence (the multi-controller
# contract).  `distributed_init` is the one-time bring-up; the
# zero-config default (knobs unset, or one process) stays exactly the
# single-process mesh of PR 8.


class DistConfig(NamedTuple):
    coordinator: str  # host:port of process 0's coordinator service
    num_processes: int
    process_id: int


def _dist_knob(name: str, default: str) -> str:
    """One NOMAD_TPU_DIST_* knob, namespaced.  With NOMAD_TPU_DIST_NS
    set (say ``f1``), ``NOMAD_TPU_DIST_COORD_F1`` wins over
    ``NOMAD_TPU_DIST_COORD`` — so a composed-topology harness can
    hand every process ONE shared env block describing all N follower
    worlds, varying only the namespace selector (plus the member id)
    per process, and no follower can accidentally join another
    follower's world by inheriting an un-namespaced coordinator."""
    ns = os.environ.get("NOMAD_TPU_DIST_NS", "")
    if ns:
        val = os.environ.get(f"{name}_{ns.upper()}")
        if val is not None:
            return val
    return os.environ.get(name, default)


def dist_config() -> Optional[DistConfig]:
    """The NOMAD_TPU_DIST_* knobs, or None when multi-host is not
    opted into (`NOMAD_TPU_DIST` != 1).  With the opt-in set, a
    malformed process count / id RAISES instead of being coerced: a
    member silently degrading to single-host is exactly the
    peer-deadlock the loud-failure contract exists to prevent."""
    if os.environ.get("NOMAD_TPU_DIST") != "1":
        return None
    coord = _dist_knob(
        "NOMAD_TPU_DIST_COORD", "127.0.0.1:8476"
    )
    try:
        procs = int(_dist_knob("NOMAD_TPU_DIST_PROCS", "1"))
        pid = int(_dist_knob("NOMAD_TPU_DIST_ID", "0"))
    except ValueError as exc:
        raise ValueError(
            "NOMAD_TPU_DIST=1 but NOMAD_TPU_DIST_PROCS/"
            "NOMAD_TPU_DIST_ID are not integers — refusing to "
            "guess: a member that silently fell back to "
            "single-host would deadlock its peers' first "
            f"collective ({exc})"
        ) from exc
    if procs <= 1:
        # documented off-switch: <=1 keeps distributed init off
        return DistConfig(coord, 1, 0)
    if not 0 <= pid < procs:
        raise ValueError(
            f"NOMAD_TPU_DIST_ID={pid} out of range for "
            f"NOMAD_TPU_DIST_PROCS={procs}"
        )
    return DistConfig(coord, procs, pid)


_dist_initialized = False


def distributed_init() -> bool:
    """Idempotent `jax.distributed.initialize` from the
    NOMAD_TPU_DIST_* knobs.  Returns True when this process is part
    of a live multi-process world, False for the single-process
    default (knobs unset, or NOMAD_TPU_DIST_PROCS <= 1 — with one
    process nothing needs a coordinator, and calling initialize after
    the backend warmed up would be an error in embedding tests).

    Must run before the first backend touch (`jax.devices()` et al.);
    `make_mesh` and the BatchWorker's mesh construction both call it
    first, so a server whose operator set the knobs joins the pod
    before any kernel compiles.  A misconfigured world (bad
    coordinator, wrong process count) RAISES rather than silently
    degrading to single-process: the peers would deadlock waiting for
    this process inside their first collective.

    On the CPU backend (the tier-1-hermetic harness: spawned local
    processes) cross-process collectives need the gloo implementation;
    it is selected here before the backend initializes.
    """
    global _dist_initialized
    cfg = dist_config()
    if cfg is None or cfg.num_processes <= 1:
        return False
    if _dist_initialized:
        return True
    from ..device_lock import _cpu_only

    plats = os.environ.get("JAX_PLATFORMS", "")
    if not plats or _cpu_only(plats):
        # CPU multiprocess computations are only implemented over
        # gloo; must be picked before the backend client exists.
        # Unset JAX_PLATFORMS counts too — a host whose backend
        # merely RESOLVES to cpu would otherwise handshake fine and
        # then stall every peer at the first collective (the late,
        # pod-wide failure the loud-misconfig contract forbids)
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo"
            )
        except Exception:
            if _cpu_only(plats):
                # an explicitly-CPU world cannot collectivize
                # without gloo — fail now, not mid-chain
                raise
            # unset platform on an accelerator build without the
            # option: the accelerator runtime owns collectives
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    _dist_initialized = True
    # Touch the backend NOW: the global-topology exchange only
    # completes once every process has initialized its local backend,
    # and jaxlib gives the laggard a hard 5-minute deadline.  A head
    # whose first mesh launch arrives later than that (quiet follower,
    # slow machine) would kill every peer blocked in jax.devices() —
    # warming eagerly makes world formation independent of when the
    # scheduler first needs the mesh.
    jax.devices()
    return True


def host_count(mesh: Mesh) -> int:
    """Distinct processes contributing devices to this mesh."""
    return len({d.process_index for d in mesh.devices.flat})


def is_multihost(mesh: Mesh) -> bool:
    return host_count(mesh) > 1


def local_device_positions(mesh: Mesh) -> list:
    """Positions along the mesh's flattened device order owned by
    THIS process — the rows of a per-device staging stack this host
    actually ships (everything else is another host's slice)."""
    me = jax.process_index()
    return [
        i
        for i, d in enumerate(mesh.devices.flat)
        if d.process_index == me
    ]


def local_device_count(mesh: Mesh) -> int:
    """This process's devices on the mesh's node axis — the divisor
    of every per-host traffic figure."""
    return len(local_device_positions(mesh))


def mesh_put(mesh: Mesh, arr, spec) -> jax.Array:
    """Commit a host array onto the mesh under ``spec``.  Fully
    addressable (single process): a plain ``device_put`` — byte-for-
    byte the PR 8 path.  Multi-host: ``make_array_from_callback``, so
    each process stages ONLY its own addressable shards (a replicated
    spec stages one copy per local device; a P("nodes") column stages
    this host's rows and nothing else) — no host ever ships another
    host's slice, and no full column crosses the network."""
    sh = NamedSharding(mesh, spec)
    if sh.is_fully_addressable:
        return jax.device_put(arr, sh)
    host = np.asarray(arr)
    return jax.make_array_from_callback(
        host.shape, sh, lambda idx: host[idx]
    )


def make_mesh(
    n_devices: Optional[int] = None,
    eval_axis: Optional[int] = None,
    backend: Optional[str] = None,
) -> Mesh:
    """Build an (evals, nodes) mesh over the available devices.  When the
    default backend has fewer devices than requested, fall back to the
    CPU backend (virtual host devices for sharding tests).

    With the NOMAD_TPU_DIST_* knobs set, `distributed_init` joins the
    multi-process world first and ``jax.devices()`` returns EVERY
    host's devices — the node axis then spans the whole pod and the
    same sharded programs run unchanged across processes."""
    from ..device_lock import align_jax_platforms

    align_jax_platforms()
    distributed_init()
    devices = jax.devices(backend) if backend else jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n_devices:
                devices = cpu
        except RuntimeError:
            pass
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if eval_axis is None:
        # favor the node axis: it is the long dimension
        eval_axis = 2 if (n % 2 == 0 and n >= 4) else 1
    node_axis = n // eval_axis
    mesh_devices = np.asarray(devices).reshape(eval_axis, node_axis)
    return Mesh(mesh_devices, axis_names=("evals", "nodes"))


def node_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("nodes"))


def eval_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("evals"))


def sharded_score_and_select(mesh: Mesh, spread_fit: bool = False):
    """The node-sharded single-placement kernel: each device scores its
    shard of the node arena locally (O(N/devices) work, columns resident
    per shard), the per-node score/feasibility vectors are all-gathered
    over ICI, and the selection walk runs replicated — bit-identical to
    the single-chip kernel.

    ScoreInputs layout: node-indexed fields sharded P('nodes'); `perm`
    and scalars replicated.
    """
    node_fields = ScoreInputs(
        cpu_total=P("nodes"),
        mem_total=P("nodes"),
        disk_total=P("nodes"),
        cpu_used=P("nodes"),
        mem_used=P("nodes"),
        disk_used=P("nodes"),
        feasible=P("nodes"),
        collisions=P("nodes"),
        penalty=P("nodes"),
        affinity_score=P("nodes"),
        spread_boost=P("nodes"),
        perm=P(),
        ask_cpu=P(),
        ask_mem=P(),
        ask_disk=P(),
        desired_count=P(),
        limit=P(),
        n_candidates=P(),
    )

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(node_fields,),
        out_specs=(P(), P(), P(), P()),
    )
    def _run(inp: ScoreInputs):
        feasible, final = _score_vectors(inp, spread_fit)
        final = jax.lax.all_gather(final, "nodes", axis=0, tiled=True)
        feasible = jax.lax.all_gather(
            feasible, "nodes", axis=0, tiled=True
        )
        return _limited_walk_argmax(
            feasible, final, inp.perm, inp.limit, inp.n_candidates
        )

    return _run


def _sharded_walk(final_full, feas_full, perm, off, lim, nc,
                  shard, n_dev, shard_size):
    """The rotating limited-walk selection with the O(C) math sharded
    along the PERM axis: each device walks its contiguous slice of the
    permuted ordering; global prefix counts come from a local cumsum
    plus an exchanged per-shard carry (classic parallel scan), and the
    winner/pulls reductions exchange only O(devices) scalars.  Decisions
    are bit-identical to ops/batch._walk."""
    from ..ops.score import MAX_SKIP, NO_NODE, SKIP_THRESHOLD

    big = jnp.asarray(2**31 - 1, jnp.int32)
    lo = shard * shard_size
    pos_l = lo + jnp.arange(shard_size, dtype=jnp.int32)
    perm_l = jax.lax.dynamic_slice_in_dim(perm, lo, shard_size)
    s_l = final_full[perm_l]
    f_l = feas_full[perm_l]
    is_tail = pos_l >= nc
    in_wrap = pos_l < off
    wp_l = jnp.where(
        is_tail, pos_l, jnp.mod(pos_l - off + nc, nc)
    )

    off_shard = (off - 1) // shard_size
    off_local = jnp.mod(off - 1, shard_size)

    def rot(b_l):
        local_cs = jnp.cumsum(b_l.astype(jnp.int32))
        sums = jax.lax.all_gather(local_cs[-1], "nodes")  # (D,)
        carry = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(sums)[:-1]]
        )[shard]
        cs_l = local_cs + carry
        total = jnp.sum(sums)
        own = shard == off_shard
        c_off_val = jax.lax.psum(
            jnp.where(own, jnp.take(cs_l, off_local), 0), "nodes"
        )
        c_off = jnp.where(off > 0, c_off_val, 0)
        pre = jnp.where(in_wrap, cs_l + (total - c_off), cs_l - c_off)
        return jnp.where(is_tail, total, pre), total

    bad = f_l & (s_l <= SKIP_THRESHOLD)
    bad_rank, _ = rot(bad)
    diverted = bad & (bad_rank <= MAX_SKIP)
    nd = f_l & ~diverted
    nd_incl, nd_count = rot(nd)
    div_incl, n_div = rot(diverted)
    div_rank = div_incl - 1
    # reversal only when a non-diverted emission preceded the replay
    # (see ops/score.py _limited_walk_argmax)
    div_order = jnp.where(
        (n_div == 2) & (nd_count > 0), 1 - div_rank, div_rank
    )
    emit_order = jnp.where(nd, nd_incl - 1, nd_count + div_order)
    emitted = f_l & (emit_order < lim)

    neg_inf = jnp.asarray(-jnp.inf, dtype=s_l.dtype)
    masked = jnp.where(emitted, s_l, neg_inf)
    best = jax.lax.pmax(jnp.max(masked), "nodes")
    candidates = emitted & (masked == best)
    order_key = jnp.where(candidates, emit_order, big)
    local_win = jnp.argmin(order_key)
    local_key = jnp.take(order_key, local_win)
    gmin = jax.lax.pmin(local_key, "nodes")
    win_pos = jax.lax.pmin(
        jnp.where(
            local_key == gmin,
            (lo + local_win).astype(jnp.int32),
            big,
        ),
        "nodes",
    )
    any_emitted = jax.lax.pmax(jnp.any(emitted), "nodes")

    limit_reached = nd_count >= lim
    lth_wp = jax.lax.pmin(
        jnp.min(jnp.where(nd & (nd_incl == lim), wp_l, big)),
        "nodes",
    )
    pulls = jnp.where(limit_reached, lth_wp + 1, nc)
    row = jnp.where(any_emitted, perm[win_pos], NO_NODE)
    return row, any_emitted, pulls


def chain_in_specs(
    with_spread: bool = False, spread_even: bool = False
) -> tuple:
    """The sharded chained runner's input PartitionSpecs, positionally
    aligned with `sharded_chained_plan`'s argument tuple.  Shared by
    the runner itself and `place_chain_inputs` (the multi-host launch
    staging), so the two cannot drift."""
    from ..ops.batch import PreDeltas, SpreadInputs, StepDeltas

    col = P("nodes")
    in_specs = (
        col, col, col,            # totals
        col, col, col,            # used0
        P(None, "nodes"),         # feasible [E, C]
        P(),                      # perm [E, C] replicated (global ids)
        P(), P(), P(),            # asks [E]
        P(),                      # desired_count [E]
        P(),                      # limit [E]
        P(),                      # wanted [E]
        P(),                      # n_candidates [E]
        P(),                      # distinct_hosts [E]
        P(None, "nodes"),         # coll0 [E, C]
        P(None, "nodes"),         # affinity [E, C]
        StepDeltas(               # leading axis E, row-space
            evict_rows=P(), evict_cpu=P(), evict_mem=P(),
            evict_disk=P(), evict_coll=P(), penalty_rows=P(),
        ),
        PreDeltas(rows=P(), cpu=P(), mem=P(), disk=P()),
    )
    if with_spread:
        in_specs = in_specs + (
            SpreadInputs(              # leading axis E
                codes=P(None, None, "nodes"),  # [E, S, C]
                desired=P(), used0=P(), proposed0=P(),
                cleared0=P(), weight=P(), active=P(),
                # percent-only batches pass even=None (skips tracing
                # the min/max block, mirroring the unsharded kernel)
                even=P() if spread_even else None,
            ),
        )
    return in_specs


def place_chain_inputs(
    mesh: Mesh, args: tuple,
    with_spread: bool = False, spread_even: bool = False,
) -> tuple:
    """Commit a chunk launch's host-staged arguments onto a MULTI-host
    mesh under the runner's own in_specs: node-axis leaves land as each
    process's own shard slices, per-eval leaves replicate onto local
    devices only, and already-committed device arrays (the sharded
    usage mirror, the previous chunk's carry) pass through untouched.
    Single-process launches never need this — jit places host arrays
    itself — but a multi-controller jit cannot conjure a global array
    from process-local host data."""
    specs = chain_in_specs(with_spread, spread_even)

    def place(a, s):
        if a is None:
            return None
        if hasattr(a, "_fields"):  # NamedTuple-of-arrays inputs
            return type(a)(
                *[place(f, sf) for f, sf in zip(a, s)]
            )
        if isinstance(a, jax.Array):  # carry / mirror: committed
            return a
        return mesh_put(mesh, a, s)

    return tuple(place(a, s) for a, s in zip(args, specs))


def sharded_chained_plan(mesh: Mesh, n_picks: int,
                         spread_fit: bool = False,
                         with_spread: bool = False,
                         spread_even: bool = False,
                         return_carry: bool = False):
    """The production chained planner with REAL node-axis sharding:
    every per-pick quantity that is O(nodes) — fit masks, fitness,
    anti-affinity, penalties, usage scatter — is computed on the
    device's own node shard (O(C/devices) FLOPs per device), and only
    the per-pick score/feasibility vectors are all-gathered over ICI
    for the replicated limited-walk selection (f64+bool per node, tens
    of KB at 10k nodes).  Serially equivalent across evals exactly like
    `chained_plan_picks_cols`: the sharded usage columns carry forward
    through the eval scan.

    Scope: single-group shapes (no ports/devices in the sharded
    variant).  ``with_spread=True`` adds the in-kernel spread carry
    (VERDICT r4 #9: spread streams must exercise the multi-chip path):
    the per-node spread contributions (percent AND even mode) compute
    on each shard from its own codes slice, the small (S, V+1)
    proposed/cleared carries stay replicated, and the winner's /
    evictee's value-slot one-hots reduce over shards with one psum per
    pick.  Decisions are bit-identical to the unsharded kernel — the
    walk consumes the same score vector in the same order.

    Returns ``run(cpu_total, mem_total, disk_total, used0_cpu,
    used0_mem, used0_disk, feasible[E,C], perm[E,C], asks..., wanted,
    limits, n_candidates, coll0[E,C], deltas, pre) ->
    (rows[E,P], pulls[E,P])``.  ``pulls`` is the per-pick
    source-iterator consumption — identical to the unsharded kernel's,
    so mesh-path preempt retries replay through the same passthrough
    machinery as the serial chain.

    With ``return_carry=True`` the final eval-scan carry — the chained
    (cpu, mem, disk) usage columns, still sharded ``P("nodes")`` — is
    returned as a third output.  Feeding it into the next launch's
    ``used0_*`` is bit-identical to one longer launch (a lax.scan cut
    at an eval boundary), which is what lets the mesh path run through
    the BatchWorker's double-buffered chunk pipeline: the sharded
    usage columns thread chunk -> chunk entirely on-device.  The
    ``used0_*`` inputs may be host arrays or device-resident
    ``NamedSharding(P("nodes"))`` arrays (the sharded usage mirror /
    the previous chunk's carry) — no resharding happens either way.
    """
    from ..ops.batch import spread_contribution
    from ..ops.score import NO_NODE

    n_dev = mesh.devices.size
    col = P("nodes")

    in_specs = chain_in_specs(with_spread, spread_even)

    # rows/pulls are replicated by construction (post-all-gather walk);
    # the usage carry stays sharded along the node axis so a chunked
    # chain never gathers it
    out_specs = (P(), P())
    if return_carry:
        out_specs = out_specs + ((col, col, col),)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
    )
    def _run(
        cpu_total, mem_total, disk_total,
        used0_cpu, used0_mem, used0_disk,
        feasible_all, perm_all,
        ask_cpu, ask_mem, ask_disk,
        desired_count, limits, wanted, n_candidates,
        distinct_hosts, coll0_all, affinity_all, deltas, pre,
        *spread_xs,
    ):
        spread_all = spread_xs[0] if with_spread else None
        shard = jax.lax.axis_index("nodes")
        shard_size = cpu_total.shape[0]
        lo = shard * shard_size

        safe_cpu = jnp.where(cpu_total > 0, cpu_total, 1.0)
        safe_mem = jnp.where(mem_total > 0, mem_total, 1.0)
        dtype = cpu_total.dtype

        def local_scatter(colv, row, delta, pred):
            idx = row - lo
            ok = pred & (idx >= 0) & (idx < shard_size)
            safe = jnp.clip(idx, 0, shard_size - 1)
            return colv.at[safe].add(
                jnp.where(ok, delta, jnp.zeros_like(delta))
            )

        def eval_step(used, xs):
            (feas_l, perm, a_cpu, a_mem, a_disk, desired, lim, w,
             nc, dh, coll_l, aff_l, d, p) = xs[:14]
            sp = xs[14] if with_spread else None
            cpu_u, mem_u, disk_u = used
            if sp is not None:
                # per-shard static spread state (mirrors the unsharded
                # kernel's hoisted lookups, on this shard's codes)
                dtype_s = cpu_total.dtype
                _S, V1 = sp.desired.shape
                onehot_l = jax.nn.one_hot(
                    sp.codes, V1, dtype=dtype_s
                )  # (S, Cl, V1)
                desired_node_l = jnp.einsum(
                    "scv,sv->sc", onehot_l, sp.desired
                )
                penalty_node_l = sp.codes == (V1 - 1)
                safe_desired_l = jnp.where(
                    desired_node_l != 0, desired_node_l, 1.0
                )
                spread_existing = sp.used0.astype(dtype_s)

                def slot_onehot(row, pred):
                    # the row's value-slot one-hot, reduced over
                    # shards: the owner contributes, others zero
                    idx = row - lo
                    mine = pred & (idx >= 0) & (idx < shard_size)
                    safe = jnp.clip(idx, 0, shard_size - 1)
                    oh = onehot_l[:, safe, :]  # (S, V1)
                    local = jnp.where(mine, oh, 0.0)
                    return jax.lax.psum(local, "nodes")
            # pre-placement deltas (row space, applied to local shard)
            def apply_pre(colv, vals):
                out = colv
                # R is small; scan-free loop unrolled by XLA
                def body(i, acc):
                    return local_scatter(
                        acc, p.rows[i], vals[i].astype(acc.dtype),
                        jnp.asarray(True),
                    )
                return jax.lax.fori_loop(
                    0, p.rows.shape[0], body, out
                )
            cpu_u = apply_pre(cpu_u, p.cpu)
            mem_u = apply_pre(mem_u, p.mem)
            disk_u = apply_pre(disk_u, p.disk)

            def pick_step(carry, k):
                if sp is not None:
                    (cpu_c, mem_c, disk_c, coll_c, pen_c, off,
                     dead, spread_prop, spread_clr) = carry
                else:
                    (cpu_c, mem_c, disk_c, coll_c, pen_c, off,
                     dead) = carry
                    spread_prop = spread_clr = None
                active = (k < w) & ~dead
                erow = d.evict_rows[k]
                app = active & (erow >= 0)
                if sp is not None:
                    # the evicted alloc's value slot gains one cleared
                    # use BEFORE this pick scores (propertyset counts
                    # the staged stop as cleared)
                    spread_clr = spread_clr + slot_onehot(erow, app)
                cpu_c = local_scatter(
                    cpu_c, erow, d.evict_cpu[k].astype(dtype), app
                )
                mem_c = local_scatter(
                    mem_c, erow, d.evict_mem[k].astype(dtype), app
                )
                disk_c = local_scatter(
                    disk_c, erow, d.evict_disk[k].astype(dtype), app
                )
                coll_c = local_scatter(
                    coll_c, erow, d.evict_coll[k], app
                )
                prow = d.penalty_rows[k]  # (K,) global rows
                local_rows = lo + jnp.arange(shard_size)
                pen_now = pen_c | jnp.any(
                    local_rows[:, None] == prow[None, :], axis=1
                )
                # local scoring (O(C/devices))
                cpu_after = cpu_c + a_cpu
                mem_after = mem_c + a_mem
                disk_after = disk_c + a_disk
                fit = (
                    (cpu_after <= cpu_total)
                    & (mem_after <= mem_total)
                    & (disk_after <= disk_total)
                )
                # distinct_hosts via the collision carry, as in the
                # unsharded kernel
                feas = feas_l & fit & ~(dh & (coll_c > 0))
                free_cpu = 1.0 - cpu_after / safe_cpu
                free_mem = 1.0 - mem_after / safe_mem
                base = (
                    jnp.power(jnp.asarray(10.0, dtype), free_cpu)
                    .astype(jnp.float32).astype(dtype)
                    + jnp.power(jnp.asarray(10.0, dtype), free_mem)
                    .astype(jnp.float32).astype(dtype)
                )
                if spread_fit:
                    fitness = jnp.clip(base - 2.0, 0.0, 18.0)
                else:
                    fitness = jnp.clip(20.0 - base, 0.0, 18.0)
                score_sum = fitness / 18.0
                count = jnp.ones_like(score_sum)
                has_coll = coll_c > 0
                anti = jnp.where(
                    has_coll,
                    -(coll_c.astype(dtype) + 1.0)
                    / desired.astype(dtype),
                    0.0,
                )
                score_sum = score_sum + anti
                count = count + has_coll.astype(dtype)
                score_sum = score_sum - pen_now.astype(dtype)
                count = count + pen_now.astype(dtype)
                has_aff = aff_l != 0.0
                score_sum = score_sum + jnp.where(has_aff, aff_l, 0.0)
                count = count + has_aff.astype(dtype)
                if sp is not None:
                    # spread boost per stanza on this shard's nodes —
                    # the (S, V+1) carries are replicated, so the
                    # combined-use math is collective-free; only the
                    # winner/evictee one-hots psum (slot_onehot).
                    # Shared implementation with the unsharded kernel
                    # (spread_contribution) so the two cannot drift.
                    spread_total_l = spread_contribution(
                        onehot_l, desired_node_l, penalty_node_l,
                        safe_desired_l, spread_existing,
                        spread_prop, spread_clr, sp.weight,
                        sp.active, sp.even, dtype,
                    )
                    has_spread = spread_total_l != 0.0
                    score_sum = score_sum + spread_total_l
                    count = count + has_spread.astype(dtype)
                final_l = score_sum / count

                # the ONLY cross-shard traffic: the per-node score +
                # feasibility vectors (for the permuted re-slice) and
                # O(devices) walk carries
                final = jax.lax.all_gather(
                    final_l, "nodes", axis=0, tiled=True
                )
                feas_full = jax.lax.all_gather(
                    feas, "nodes", axis=0, tiled=True
                )
                win_row, any_emitted, pulls = _sharded_walk(
                    final, feas_full, perm, off, lim, nc,
                    shard, n_dev, shard_size,
                )
                ok = active & any_emitted
                dead = dead | (active & ~any_emitted)
                row = jnp.where(ok, win_row, NO_NODE)
                # per-pick source consumption, surfaced exactly like
                # the unsharded kernel (inactive picks pull nothing)
                pulls_out = jnp.where(active, pulls, 0)
                cpu_c = local_scatter(
                    cpu_c, row, jnp.asarray(a_cpu, dtype), ok
                )
                mem_c = local_scatter(
                    mem_c, row, jnp.asarray(a_mem, dtype), ok
                )
                disk_c = local_scatter(
                    disk_c, row, jnp.asarray(a_disk, dtype), ok
                )
                coll_c = local_scatter(
                    coll_c, row, jnp.asarray(1, jnp.int32), ok
                )
                off = jnp.mod(
                    off + jnp.where(active, pulls, 0), nc
                )
                if sp is not None:
                    # the placed node's value slot gains one proposed
                    # use per stanza
                    spread_prop = spread_prop + slot_onehot(row, ok)
                    return (
                        cpu_c, mem_c, disk_c, coll_c, pen_c, off,
                        dead, spread_prop, spread_clr,
                    ), (row, pulls_out)
                return (
                    cpu_c, mem_c, disk_c, coll_c, pen_c, off, dead
                ), (row, pulls_out)

            carry0 = (
                cpu_u, mem_u, disk_u, coll_l,
                jnp.zeros(shard_size, dtype=bool),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(False),
            )
            if sp is not None:
                carry0 = carry0 + (
                    sp.proposed0.astype(cpu_total.dtype),
                    sp.cleared0.astype(cpu_total.dtype),
                )
            final_carry, (rows, pulls) = jax.lax.scan(
                pick_step, carry0,
                jnp.arange(n_picks, dtype=jnp.int32),
            )
            return (
                (final_carry[0], final_carry[1], final_carry[2]),
                (rows, pulls),
            )

        used0 = (used0_cpu, used0_mem, used0_disk)
        xs_all = (
            feasible_all, perm_all, ask_cpu, ask_mem, ask_disk,
            desired_count, limits, wanted, n_candidates,
            distinct_hosts, coll0_all, affinity_all, deltas, pre,
        )
        if with_spread:
            xs_all = xs_all + (spread_all,)
        final, (rows, pulls) = jax.lax.scan(eval_step, used0, xs_all)
        if return_carry:
            return rows, pulls, final
        return rows, pulls

    return _run


def sharded_batch_plan(
    mesh: Mesh,
    n_candidates: int,
    n_picks: int,
    spread_fit: bool = False,
):
    """Build the sharded batched planner: node columns sharded over the
    ``nodes`` axis, the eval batch sharded over ``evals``; scoring is
    local, score vectors are all-gathered over ``nodes`` for the
    replicated selection walk.

    Returns a function
    ``(cpu_total, mem_total, disk_total, batch: BatchInputs) -> rows[E,P]``
    whose arguments may be host arrays; shardings are applied via
    `jax.device_put` inside.
    """

    col_spec = P("nodes")
    # per-eval fields: node-indexed ones shard on both axes, scalars on
    # evals only
    batch_spec = BatchInputs(
        feasible=P("evals", "nodes"),
        base_cpu_used=P("evals", "nodes"),
        base_mem_used=P("evals", "nodes"),
        base_disk_used=P("evals", "nodes"),
        base_collisions=P("evals", "nodes"),
        penalty=P("evals", "nodes"),
        affinity_score=P("evals", "nodes"),
        perm=P("evals", "nodes"),
        ask_cpu=P("evals"),
        ask_mem=P("evals"),
        ask_disk=P("evals"),
        desired_count=P("evals"),
        limit=P("evals"),
        distinct_hosts=P("evals"),
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(col_spec, col_spec, col_spec, batch_spec),
        out_specs=P("evals"),
    )
    def _run(cpu_total, mem_total, disk_total, batch: BatchInputs):
        # gather full node columns over the nodes axis (ICI all-gather);
        # the walk needs the global ordering
        gather = lambda x: jax.lax.all_gather(
            x, "nodes", axis=0, tiled=True
        )
        cpu_t = gather(cpu_total)
        mem_t = gather(mem_total)
        disk_t = gather(disk_total)

        def one_eval(b: BatchInputs):
            full = BatchInputs(
                feasible=gather(b.feasible),
                base_cpu_used=gather(b.base_cpu_used),
                base_mem_used=gather(b.base_mem_used),
                base_disk_used=gather(b.base_disk_used),
                base_collisions=gather(b.base_collisions),
                penalty=gather(b.penalty),
                affinity_score=gather(b.affinity_score),
                perm=gather(b.perm),
                ask_cpu=b.ask_cpu,
                ask_mem=b.ask_mem,
                ask_disk=b.ask_disk,
                desired_count=b.desired_count,
                limit=b.limit,
                distinct_hosts=b.distinct_hosts,
            )
            return plan_picks(
                cpu_t,
                mem_t,
                disk_t,
                full,
                jnp.asarray(n_candidates, jnp.int32),
                n_picks,
                spread_fit,
            )

        return jax.vmap(one_eval)(batch)

    def run(cpu_total, mem_total, disk_total, batch: BatchInputs):
        return _run(cpu_total, mem_total, disk_total, batch)

    return run
