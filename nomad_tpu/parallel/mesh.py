"""Device mesh + shardings for multi-chip scheduling.

The reference scales by running `NumSchedulers` workers per server and
federating regions over Serf/Raft (SURVEY.md section 2.10); the TPU-native
equivalents are two mesh axes:

* ``evals`` — data parallelism over independent evaluations (the unit the
  reference parallelizes across workers; broker dedup keeps them
  conflict-light, the plan applier serializes the rest);
* ``nodes`` — the long axis: the cluster's node table sharded across
  chips, the honest analog of sequence/context parallelism for a cluster
  scheduler (SURVEY.md section 5 "long-context").

Scoring is embarrassingly parallel along ``nodes``; the only cross-shard
communication is an all-gather of the per-node score/feasibility vectors
(f32 + bool per node — tens of KB at 10k nodes, ICI-cheap) before the
selection walk, which every device then computes identically (replicated,
deterministic).  This keeps the walk bit-identical to the single-chip
path while the O(N x terms) scoring work and the node-column residency
scale with the mesh.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f=None, **kwargs):
    """shard_map with replication checking off: the selection walk's
    outputs are replicated by construction (post-all-gather), which the
    static varying-axes inference cannot prove."""
    for flag in ("check_vma", "check_rep"):
        try:
            return _shard_map(f, **kwargs, **{flag: False})
        except TypeError:
            continue
    return _shard_map(f, **kwargs)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.batch import BatchInputs, plan_picks
from ..ops.score import ScoreInputs, _limited_walk_argmax, _score_vectors


def make_mesh(
    n_devices: Optional[int] = None,
    eval_axis: Optional[int] = None,
    backend: Optional[str] = None,
) -> Mesh:
    """Build an (evals, nodes) mesh over the available devices.  When the
    default backend has fewer devices than requested, fall back to the
    CPU backend (virtual host devices for sharding tests)."""
    devices = jax.devices(backend) if backend else jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n_devices:
                devices = cpu
        except RuntimeError:
            pass
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if eval_axis is None:
        # favor the node axis: it is the long dimension
        eval_axis = 2 if (n % 2 == 0 and n >= 4) else 1
    node_axis = n // eval_axis
    mesh_devices = np.asarray(devices).reshape(eval_axis, node_axis)
    return Mesh(mesh_devices, axis_names=("evals", "nodes"))


def node_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("nodes"))


def eval_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("evals"))


def sharded_score_and_select(mesh: Mesh, spread_fit: bool = False):
    """The node-sharded single-placement kernel: each device scores its
    shard of the node arena locally (O(N/devices) work, columns resident
    per shard), the per-node score/feasibility vectors are all-gathered
    over ICI, and the selection walk runs replicated — bit-identical to
    the single-chip kernel.

    ScoreInputs layout: node-indexed fields sharded P('nodes'); `perm`
    and scalars replicated.
    """
    node_fields = ScoreInputs(
        cpu_total=P("nodes"),
        mem_total=P("nodes"),
        disk_total=P("nodes"),
        cpu_used=P("nodes"),
        mem_used=P("nodes"),
        disk_used=P("nodes"),
        feasible=P("nodes"),
        collisions=P("nodes"),
        penalty=P("nodes"),
        affinity_score=P("nodes"),
        spread_boost=P("nodes"),
        perm=P(),
        ask_cpu=P(),
        ask_mem=P(),
        ask_disk=P(),
        desired_count=P(),
        limit=P(),
        n_candidates=P(),
    )

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(node_fields,),
        out_specs=(P(), P(), P(), P()),
    )
    def _run(inp: ScoreInputs):
        feasible, final = _score_vectors(inp, spread_fit)
        final = jax.lax.all_gather(final, "nodes", axis=0, tiled=True)
        feasible = jax.lax.all_gather(
            feasible, "nodes", axis=0, tiled=True
        )
        return _limited_walk_argmax(
            feasible, final, inp.perm, inp.limit, inp.n_candidates
        )

    return _run


def sharded_batch_plan(
    mesh: Mesh,
    n_candidates: int,
    n_picks: int,
    spread_fit: bool = False,
):
    """Build the sharded batched planner: node columns sharded over the
    ``nodes`` axis, the eval batch sharded over ``evals``; scoring is
    local, score vectors are all-gathered over ``nodes`` for the
    replicated selection walk.

    Returns a function
    ``(cpu_total, mem_total, disk_total, batch: BatchInputs) -> rows[E,P]``
    whose arguments may be host arrays; shardings are applied via
    `jax.device_put` inside.
    """

    col_spec = P("nodes")
    # per-eval fields: node-indexed ones shard on both axes, scalars on
    # evals only
    batch_spec = BatchInputs(
        feasible=P("evals", "nodes"),
        base_cpu_used=P("evals", "nodes"),
        base_mem_used=P("evals", "nodes"),
        base_disk_used=P("evals", "nodes"),
        base_collisions=P("evals", "nodes"),
        penalty=P("evals", "nodes"),
        affinity_score=P("evals", "nodes"),
        perm=P("evals", "nodes"),
        ask_cpu=P("evals"),
        ask_mem=P("evals"),
        ask_disk=P("evals"),
        desired_count=P("evals"),
        limit=P("evals"),
        distinct_hosts=P("evals"),
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(col_spec, col_spec, col_spec, batch_spec),
        out_specs=P("evals"),
    )
    def _run(cpu_total, mem_total, disk_total, batch: BatchInputs):
        # gather full node columns over the nodes axis (ICI all-gather);
        # the walk needs the global ordering
        gather = lambda x: jax.lax.all_gather(
            x, "nodes", axis=0, tiled=True
        )
        cpu_t = gather(cpu_total)
        mem_t = gather(mem_total)
        disk_t = gather(disk_total)

        def one_eval(b: BatchInputs):
            full = BatchInputs(
                feasible=gather(b.feasible),
                base_cpu_used=gather(b.base_cpu_used),
                base_mem_used=gather(b.base_mem_used),
                base_disk_used=gather(b.base_disk_used),
                base_collisions=gather(b.base_collisions),
                penalty=gather(b.penalty),
                affinity_score=gather(b.affinity_score),
                perm=gather(b.perm),
                ask_cpu=b.ask_cpu,
                ask_mem=b.ask_mem,
                ask_disk=b.ask_disk,
                desired_count=b.desired_count,
                limit=b.limit,
                distinct_hosts=b.distinct_hosts,
            )
            return plan_picks(
                cpu_t,
                mem_t,
                disk_t,
                full,
                jnp.asarray(n_candidates, jnp.int32),
                n_picks,
                spread_fit,
            )

        return jax.vmap(one_eval)(batch)

    def run(cpu_total, mem_total, disk_total, batch: BatchInputs):
        return _run(cpu_total, mem_total, disk_total, batch)

    return run
