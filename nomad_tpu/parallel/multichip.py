"""Multi-chip sweep: the numbers behind the bench's ``multichip``
block and the driver's MULTICHIP_r*.json tail.

For each device count the sweep builds an (evals=1, nodes=d) mesh and
drives the PRODUCTION sharded chained runner
(``sharded_chained_plan(..., return_carry=True)``) exactly the way the
BatchWorker's mesh pipeline does: the eval axis split into chunk-wide
launches whose sharded usage carry threads chunk -> chunk on-device.
Three numbers per point:

* ``placements_per_sec`` — warmed wall-clock over the chunked chain
  (E evals x P picks per run, best of a few rounds);
* ``per_device_flops`` — compiled cost analysis of one chunk launch
  (XLA reports per-device FLOPs for SPMD programs, so this should
  scale ~1/devices while the replicated walk keeps a floor — the same
  quantity tests/test_parallel.py asserts on);
* ``bytes_per_flush_delta`` vs ``bytes_per_flush_full`` — the
  host->device staging bytes of one sharded-mirror delta sync
  (``patch_rows_sharded``: an i32 index buffer + f64 value buffer per
  used column, O(dirty rows)) against a full six-column re-upload
  (O(nodes)), the transfer the sharded usage mirror removed from the
  warm mesh flush.

Shapes are deliberately modest (the point is scaling ratios, not
absolute throughput) so the sweep also runs on the virtual CPU mesh
(``--xla_force_host_platform_device_count=8``) where hardware is
unavailable.

The ``multihost`` row (`multihost_point`) goes one level further: it
spawns the 2-process distributed smoke (`dist_smoke.py`) so the SAME
sharded programs run across a real jax.distributed world — end-to-end
placements/s through the worker pipeline, per-HOST bytes per warm
flush (the cross-host delta protocol), and the storm solve sharded
vs single-device with its bit-parity verdict.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np


def _chain_inputs(C: int, E: int, P: int, seed: int = 3):
    """Synthetic single-group chained inputs in the sharded runner's
    per-eval scalar layout (the worker's T=1 slices)."""
    from ..ops.batch import PreDeltas, StepDeltas

    rng = np.random.default_rng(seed)
    n_cand = C - 8
    K, R = 2, 1
    perms = np.stack(
        [
            np.concatenate(
                [rng.permutation(n_cand), np.arange(n_cand, C)]
            )
            for _ in range(E)
        ]
    ).astype(np.int32)
    feas = np.zeros((E, C), dtype=bool)
    feas[:, :n_cand] = rng.random((E, n_cand)) > 0.1
    cols = (
        np.full(C, 8000.0),
        np.full(C, 16384.0),
        np.full(C, 100_000.0),
        rng.integers(0, 2000, C).astype(np.float64),
        rng.integers(0, 4096, C).astype(np.float64),
        np.zeros(C),
    )
    per_eval = (
        feas,
        perms,
        np.full(E, 500.0),
        np.full(E, 256.0),
        np.full(E, 300.0),
        np.full(E, P, np.int32),  # desired_count
        np.full(E, 9, np.int32),  # limit
        np.full(E, P, np.int32),  # wanted
        np.full(E, n_cand, np.int32),
        np.zeros(E, dtype=bool),  # distinct_hosts
        np.zeros((E, C), np.int32),  # coll0
        np.zeros((E, C)),  # affinity
        StepDeltas(
            evict_rows=np.full((E, P), -1, np.int32),
            evict_cpu=np.zeros((E, P)),
            evict_mem=np.zeros((E, P)),
            evict_disk=np.zeros((E, P)),
            evict_coll=np.zeros((E, P), np.int32),
            penalty_rows=np.full((E, P, K), -1, np.int32),
        ),
        PreDeltas(
            rows=np.zeros((E, R), np.int32),
            cpu=np.zeros((E, R)),
            mem=np.zeros((E, R)),
            disk=np.zeros((E, R)),
        ),
    )
    return cols, per_eval


def _slice_eval(per_eval, a: int, b: int):
    out: List[object] = []
    for x in per_eval:
        if isinstance(x, np.ndarray):
            out.append(x[a:b])
        else:
            out.append(type(x)(*[f[a:b] for f in x]))
    return tuple(out)


def _mirror_sync_bytes(C: int, dirty_rows: int) -> dict:
    """Staging bytes of one sharded-mirror sync, computed from the
    exact buffers ``BatchWorker._device_columns_locked`` ships — and
    therefore equal to what the ``mesh.bytes_per_flush`` gauge reads
    for the same sync: each of the three used columns stages its own
    pow2-padded i32 index buffer plus an f64 value buffer on the
    delta path; the full path uploads six C-row f64 columns."""
    from ..ops.batch import pow2_bucket

    width = pow2_bucket(max(dirty_rows, 1), floor=8)
    return {
        "dirty_rows": dirty_rows,
        "bytes_per_flush_delta": 3 * (width * 4 + width * 8),
        "bytes_per_flush_full": 6 * C * 8,
    }


def multihost_point(
    procs: int = 2, timeout: float = 420.0
) -> dict:
    """The ``multichip`` block's MULTI-host row: spawn the 2-process
    distributed smoke (CPU backend, gloo collectives; real pods run
    the same knobs over ICI/DCN) and report end-to-end placements/s
    through the distributed mesh, per-host bytes/flush (the O(dirty
    rows) delta protocol vs the full upload), and the storm solve
    sharded-vs-single-device wall time with its bit-parity verdict.
    Returns a skip row instead of raising — multi-host is a bench
    bonus, never a bench failure."""
    try:
        from .dist_smoke import launch

        row = launch(procs=procs, timeout=timeout)
    except Exception as exc:  # noqa: BLE001 — report, don't fail
        return {"procs": procs, "skipped": repr(exc)[:400]}
    return {
        "procs": row["procs"],
        "devices_per_host": row["devices_per_host"],
        "global_devices": row["global_devices"],
        "placements_per_sec": row["chain"]["placements_per_sec"],
        "bytes_per_flush_delta_per_host": row["flush"][
            "bytes_per_flush_delta_per_host"
        ],
        "bytes_per_flush_full_per_host": row["flush"][
            "bytes_per_flush_full_per_host"
        ],
        "storm_solve_single_device_ms": row["storm_kernel"][
            "single_device_ms"
        ],
        "storm_solve_sharded_ms": row["storm_kernel"][
            "sharded_ms"
        ],
        "storm_bit_identical": row["storm_kernel"][
            "bit_identical"
        ],
        "zero_lost": row["zero_lost"],
    }


def multichip_sweep(
    device_counts: Optional[Sequence[int]] = None,
    C: int = 1024,
    E: int = 16,
    P: int = 4,
    chunk: int = 8,
    dirty_rows: int = 24,
    rounds: int = 3,
    multihost: bool = True,
) -> dict:
    """Sweep the sharded chained pipeline over device counts; returns
    the bench's ``multichip`` block (including the spawned-process
    ``multihost`` row unless opted out)."""
    import jax

    from ..ops.batch import patch_rows_sharded
    from .mesh import make_mesh, sharded_chained_plan

    n_avail = len(jax.devices())
    if device_counts is None:
        device_counts = [d for d in (1, 2, 4, 8) if d <= n_avail]
        if not device_counts:
            device_counts = [1]
    points = []
    for d in device_counts:
        mesh = make_mesh(int(d), eval_axis=1)
        if mesh.devices.size != d:
            points.append(
                {"n_devices": int(d), "skipped": "devices"}
            )
            continue
        runner = sharded_chained_plan(mesh, P, return_carry=True)
        cols, per_eval = _chain_inputs(C, E, P)

        def run_chain():
            carry = cols[3:6]
            rows_out = []
            for a in range(0, E, chunk):
                rows, _pulls, carry = runner(
                    *cols[:3], *carry,
                    *_slice_eval(per_eval, a, a + chunk),
                )
                rows_out.append(rows)
            jax.block_until_ready(rows_out[-1])
            return rows_out

        run_chain()  # warm the (chunk, P) trace
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            run_chain()
            best = min(best, time.perf_counter() - t0)
        # per-device FLOPs of one chunk launch (the compiled SPMD
        # program XLA actually executes per chunk)
        lowered = runner.lower(
            *cols[:3], *cols[3:6], *_slice_eval(per_eval, 0, chunk)
        )
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) if cost else 0.0
        # one real sharded delta patch, to prove the path runs on
        # this mesh (the byte accounting itself is closed-form)
        from jax.sharding import NamedSharding, PartitionSpec

        col = jax.device_put(
            cols[3], NamedSharding(mesh, PartitionSpec("nodes"))
        )
        from ..ops.batch import pow2_bucket

        width = pow2_bucket(dirty_rows, floor=8)
        idx = np.full(width, C, np.int32)
        idx[:dirty_rows] = np.arange(dirty_rows, dtype=np.int32)
        vals = np.zeros(width)
        jax.block_until_ready(
            patch_rows_sharded(mesh)(col, idx, vals)
        )
        point = {
            "n_devices": int(d),
            "placements_per_sec": round((E * P) / best, 1),
            "chunk_width": chunk,
            "chunk_launches": -(-E // chunk),
            "per_device_flops": flops,
        }
        point.update(_mirror_sync_bytes(C, dirty_rows))
        points.append(point)
    flops_pts = [
        p for p in points if p.get("per_device_flops", 0.0) > 0.0
    ]
    block = {
        "arena_nodes": C,
        "evals": E,
        "picks": P,
        "points": points,
    }
    if multihost:
        block["multihost"] = multihost_point()
    if len(flops_pts) >= 2:
        block["flops_scaling_first_to_last"] = round(
            flops_pts[0]["per_device_flops"]
            / flops_pts[-1]["per_device_flops"],
            2,
        )
    return block
