"""Two-process distributed smoke: the tier-1-hermetic proof that the
multi-host mesh actually works, on nothing but the CPU backend.

``python -m nomad_tpu.parallel.dist_smoke`` spawns N local worker
processes (default 2), wires them into one jax.distributed world via
the production ``NOMAD_TPU_DIST_*`` knobs (gloo CPU collectives), and
drives each through the REAL pipeline in lockstep — the
multi-controller SPMD contract: every process executes the identical
launch sequence, each holding only its own node-axis shards.

Per worker, in order:

1. **Distributed init + pod mesh** — `distributed_init()` from the
   knobs, then a Server whose BatchWorker mesh spans every host's
   devices (`_mesh_hosts == procs`).
2. **Chain** — a batch of single-group jobs through the worker's own
   ``_process_batch``: the full assemble/launch/fetch/replay pipeline
   over the distributed mesh, sharded usage carry threading
   chunk -> chunk, zero lost evals.  Drives the bench row's
   end-to-end placements/s.
3. **Cross-host flush** — dirty rows from a live commit, then a warm
   sharded mirror sync: the per-host delta protocol
   (`patch_rows_hostlocal`) must stage exactly the closed-form
   O(dirty rows) bytes per host, against the O(nodes) full upload.
4. **Storm** — a same-family backlog drained by the real
   ``_maybe_drain_storm`` and solved by the NODE-SHARDED auction over
   the distributed mesh, committed through the normal fences; plus a
   kernel-level A/B asserting the sharded solve is bit-identical to
   the single-device solve (and timing both for the bench row).
5. **Cross-host parity** — placement digests allgathered across
   processes must agree exactly: every host computed the same answer
   from its own shards.

Determinism note: the workers are driven SYNCHRONOUSLY (the broker
consumer thread stays paused) with all evals enqueued before any
dispatch, admission off and the latency budget disabled — so both
processes provably issue the same collective launch sequence.  A
divergent sequence would deadlock the gloo rendezvous, which is
exactly why the production multi-host path pins compiles inline and
plans chunk widths from shared state only.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import socket
import subprocess
import sys
import time
from typing import List, Optional

# default world shape: small enough that compiles dominate nothing,
# big enough that every device owns multiple node rows and every
# phase crosses the process boundary.  The NOMAD_TPU_SMOKE_* knobs
# scale the SAME worker (one code path) from this tier-1 tiny world
# up to the bigworld reduced-scale CI drive (loadgen/bigworld_smoke)
DEVICES_PER_PROC = 2
CHAIN_NODES = 12  # -> capacity 16: tiles over 4 devices
CHAIN_JOBS = 12
FAMILY_JOBS = 16
KERNEL_E, KERNEL_A, KERNEL_C = 16, 64, 256


def _world_knob(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


def smoke_world() -> dict:
    """The world-size knobs, defaulted to the tier-1 tiny world:
    NOMAD_TPU_SMOKE_NODES (cluster size), NOMAD_TPU_SMOKE_JOBS
    (chain-phase evals), NOMAD_TPU_SMOKE_FAMILY (storm family
    size)."""
    return {
        "nodes": _world_knob("NOMAD_TPU_SMOKE_NODES", CHAIN_NODES),
        "jobs": _world_knob("NOMAD_TPU_SMOKE_JOBS", CHAIN_JOBS),
        "family": _world_knob(
            "NOMAD_TPU_SMOKE_FAMILY", FAMILY_JOBS
        ),
    }


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# worker (one process of the distributed world; env set by the launcher)
# ---------------------------------------------------------------------------


def _digest(value) -> int:
    blob = json.dumps(value, sort_keys=True, default=str)
    return int.from_bytes(
        hashlib.sha256(blob.encode()).digest()[:8], "big"
    ) % (2**62)


def _assert_same_everywhere(tag: str, value) -> None:
    """Allgather a digest of ``value`` across processes and require
    agreement — the cross-host parity fence (and a phase barrier)."""
    import numpy as np
    from jax.experimental import multihost_utils

    got = multihost_utils.process_allgather(
        np.asarray([_digest(value)], np.int64)
    ).ravel()
    if not (got == got[0]).all():
        raise AssertionError(
            f"cross-host divergence in {tag}: digests {got.tolist()}"
        )


def _make_nodes(n, seed=0):
    import random

    from nomad_tpu import mock
    from nomad_tpu.structs import compute_node_class

    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node(id=f"dist-node-{seed}-{i:03d}")
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def _make_jobs(n, prefix="dist", seed=1):
    import random

    from nomad_tpu import mock

    rng = random.Random(seed)
    jobs = []
    for i in range(n):
        job = mock.job(id=f"{prefix}-{i:03d}")
        job.task_groups[0].count = rng.randint(1, 3)
        job.task_groups[0].tasks[0].resources.cpu = rng.choice(
            [200, 400]
        )
        jobs.append(job)
    return jobs


def _family_jobs(n, fam="distfam"):
    from nomad_tpu import mock

    jobs = []
    for i in range(n):
        job = mock.job(id=f"{fam}/dispatch-{i:04d}")
        job.type = "batch"
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.cpu = 500
        job.task_groups[0].tasks[0].resources.memory_mb = 1024
        jobs.append(job)
    return jobs


def _drain_broker(server, worker, expect: int, timeout=30.0):
    """Wait until the quiescent broker holds ``expect`` ready evals,
    then dequeue them all (FIFO) — the deterministic stand-in for the
    run() gulp, taken while the consumer thread is paused."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if server.broker.ready_count(worker.schedulers) >= expect:
            break
        time.sleep(0.02)
    members = []
    for _ in range(expect):
        ev, token = server.broker.dequeue(
            worker.schedulers, timeout=5.0
        )
        assert ev is not None, (
            f"broker ran dry at {len(members)}/{expect}"
        )
        members.append((ev, token))
    return members


def _drain_residuals(server, worker, jobs, timeout=30.0):
    """Process late-arriving evals (blocked-eval requeues, watcher
    re-evaluations) until every eval is terminal and the broker is
    dry — in LOCKSTEP: each round allgathers (ready, terminal) so
    every process dequeues the same batch in the same round, keeping
    the collective launch sequences identical.  State is replicated,
    so only thread TIMING differs across processes; the barrier per
    round absorbs that skew."""
    import numpy as np
    from jax.experimental import multihost_utils

    deadline = time.monotonic() + timeout
    while True:
        ready = server.broker.ready_count(worker.schedulers)
        term = all(
            _settled(e)
            for job in jobs
            for e in server.store.evals_by_job("default", job.id)
        )
        agg = multihost_utils.process_allgather(
            np.asarray([ready, int(term)], np.int64)
        ).reshape(-1, 2)
        max_ready = int(agg[:, 0].max())
        all_term = bool(agg[:, 1].all())
        if max_ready == 0 and all_term:
            return
        assert time.monotonic() < deadline, (
            f"residual evals never settled: ready={agg[:, 0].tolist()}"
            f" terminal={agg[:, 1].tolist()}"
        )
        if max_ready > 0:
            # the same eval set exists on every process (replicated
            # state) — wait for this process's copy, then process
            # the identical batch everywhere
            while (
                server.broker.ready_count(worker.schedulers)
                < max_ready
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            batch = []
            for _ in range(max_ready):
                ev, token = server.broker.dequeue(
                    worker.schedulers, timeout=5.0
                )
                assert ev is not None, "residual eval vanished"
                batch.append((ev, token))
            leftover = worker._process_batch(batch)
            for _ in range(8):
                if not leftover:
                    break
                leftover = worker._process_batch(leftover)
            assert not leftover
        else:
            time.sleep(0.05)


def _placements(server, jobs):
    return sorted(
        (job.id, a.name, a.node_id)
        for job in jobs
        for a in server.store.allocs_by_job("default", job.id)
        if not a.terminal_status()
    )


def _settled(e) -> bool:
    """Fully processed: terminal, or parked BLOCKED for capacity —
    the zero-lost contract is "no eval stranded mid-pipeline", and a
    blocked eval was processed to completion and is waiting on a
    future capacity change, exactly like production."""
    return e.terminal_status() or e.should_block()


def _assert_zero_lost(server, jobs):
    for job in jobs:
        evs = server.store.evals_by_job("default", job.id)
        assert evs, f"no evals for {job.id}"
        bad = [
            (e.id, e.status, e.status_description)
            for e in evs
            if not _settled(e)
        ]
        assert not bad, (
            f"unsettled evals for {job.id}: {bad} "
            f"(broker ready={server.broker.ready_count(['batch', 'service'])})"
        )
    assert server.broker.failed() == []


def _kernel_storm_problem(E, A, C, dtype):
    import numpy as np

    from nomad_tpu.ops.solve import StormInputs

    rng = np.random.default_rng(17)
    perm = np.tile(rng.permutation(C).astype(np.int32), (E, 1))
    inp = StormInputs(
        feasible=rng.random((E, C)) > 0.1,
        affinity=np.where(
            rng.random((E, C)) > 0.8,
            rng.random((E, C)).astype(dtype),
            0.0,
        ).astype(dtype),
        collisions=(rng.random((E, C)) > 0.9).astype(np.int32),
        perm=perm,
        limit=np.full(E, 2, np.int32),
        n_cand=np.full(E, C, np.int32),
        eval_of=(np.arange(A) % E).astype(np.int32),
        penalty=rng.random((A, C)) > 0.95,
        ask=np.tile(
            np.asarray((1000.0, 100.0, 100.0), dtype), (A, 1)
        ),
        desired=np.ones(A, np.int32),
        real=np.ones(A, bool),
        pre_cpu=np.zeros(C, dtype),
        pre_mem=np.zeros(C, dtype),
        pre_disk=np.zeros(C, dtype),
    )
    cols = tuple(
        np.asarray(x, dtype)
        for x in (
            np.full(C, 4000.0),
            np.full(C, 8192.0),
            np.full(C, 100000.0),
            rng.integers(0, 1000, C).astype(dtype),
            np.zeros(C),
            np.zeros(C),
        )
    )
    return inp, cols


def run_worker() -> int:
    """One process of the distributed world.  Exits non-zero on any
    parity or liveness failure; rank 0 prints the result JSON."""
    assert os.environ.get("NOMAD_TPU_DIST") == "1", (
        "worker needs the NOMAD_TPU_DIST_* env (use the launcher)"
    )
    # the ONE ordering requirement: join the world before anything
    # touches the backend
    from nomad_tpu.parallel.mesh import distributed_init

    assert distributed_init(), "distributed init did not engage"
    import jax
    import numpy as np

    rank = jax.process_index()
    procs = jax.process_count()
    world = smoke_world()
    n_nodes, n_jobs, n_family = (
        world["nodes"], world["jobs"], world["family"]
    )
    result = {
        "procs": procs,
        "devices_per_host": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "world": world,
    }

    from nomad_tpu.server import Server

    # -- phase: server + pod mesh -------------------------------------
    # long heartbeat TTL: this harness drives the worker
    # synchronously and pays multi-second XLA compiles mid-phase; the
    # default 30s TTL would mark every (clientless) node down during
    # a cold compile under CI load and block all placements
    server = Server(
        num_schedulers=1, seed=29, batch_pipeline=True,
        heartbeat_ttl=600.0,
    )
    worker = server.workers[0]
    # drive the pipeline synchronously: the consumer thread never
    # starts, so the gulp composition — and with it the collective
    # launch sequence — is identical on every process
    worker.start = lambda: None  # type: ignore[method-assign]
    for node in _make_nodes(n_nodes, seed=5):
        server.register_node(node)
    chain_jobs = _make_jobs(n_jobs, seed=7)
    for job in chain_jobs:
        server.register_job(job)
    server.start()
    try:
        mesh = worker._mesh
        assert mesh is not None, "no mesh on the distributed world"
        assert mesh.devices.size == jax.device_count()
        assert worker._mesh_hosts == procs, (
            worker._mesh_hosts, procs
        )
        table = server.store.node_table
        assert table.capacity % mesh.devices.size == 0, (
            table.capacity, mesh.devices.size
        )

        # -- phase: chain (assemble/launch/fetch/replay) --------------
        members = _drain_broker(server, worker, n_jobs)
        t0 = time.monotonic()
        leftover = worker._process_batch(members)
        for _ in range(8):
            if not leftover:
                break
            leftover = worker._process_batch(leftover)
        chain_dt = time.monotonic() - t0
        assert not leftover, f"{len(leftover)} evals stuck"
        assert worker.mesh_used > 0, "sharded launches never ran"
        _drain_residuals(server, worker, chain_jobs)
        _assert_zero_lost(server, chain_jobs)
        placed = _placements(server, chain_jobs)
        assert placed, "chain placed nothing"
        _assert_same_everywhere("chain placements", placed)
        result["chain"] = {
            "evals": n_jobs,
            "placements": len(placed),
            "placements_per_sec": round(len(placed) / chain_dt, 1),
            "mesh_launches": worker.mesh_used,
        }

        # -- phase: per-host cross-host flush -------------------------
        from nomad_tpu.ops.batch import pow2_bucket
        from nomad_tpu.parallel.mesh import local_device_count

        n_dev = mesh.devices.size
        n_local = local_device_count(mesh)
        size = table.capacity // n_dev
        gen = worker._usage_cache_sharded["gen"]
        _, dirty = server.store.usage_delta_since(gen)
        worker._device_columns(table, sharded=True)
        staged = server.metrics.get_gauge("mesh.bytes_per_flush")
        full = (
            sum(
                c.nbytes
                for c in (
                    table.cpu_total, table.mem_total,
                    table.disk_total, table.cpu_used,
                    table.mem_used, table.disk_used,
                )
            )
            * n_local
            // n_dev
        )
        if dirty:
            idx = np.asarray(sorted(dirty), np.int32)
            per_dev = [
                int(((idx >= d * size) & (idx < (d + 1) * size)).sum())
                for d in range(n_dev)
            ]
            w = pow2_bucket(max(1, max(per_dev)), floor=8)
            want = n_local * w * 4 + 3 * n_local * w * 8
            assert staged == want, (staged, want, per_dev)
        else:
            assert staged == 0.0, staged
        assert staged < full, (staged, full)
        result["flush"] = {
            "dirty_rows": len(dirty),
            "bytes_per_flush_delta_per_host": staged,
            "bytes_per_flush_full_per_host": full,
        }

        # -- phase: storm (sharded auction over the pod mesh) ---------
        fam_jobs = _family_jobs(n_family)
        for job in fam_jobs:
            server.register_job(job)
        # wait for the whole wave to land, then dequeue ONE member
        # and let the REAL detector drain the family prefix — the
        # broker is quiescent, so every process sees the same storm
        deadline = time.monotonic() + 30.0
        while (
            server.broker.ready_count(worker.schedulers)
            < n_family
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        ev0, token0 = server.broker.dequeue(
            worker.schedulers, timeout=5.0
        )
        assert ev0 is not None
        assert ev0.job_id.startswith("distfam/"), (
            f"stray eval {ev0.job_id} raced the storm phase"
        )
        storm = worker._maybe_drain_storm(ev0, token0)
        assert storm is not None and len(storm) == n_family, (
            "storm detector missed the family backlog"
        )
        leftover = worker._process_storm(storm)
        for _ in range(8):
            if not leftover:
                break
            leftover = worker._process_batch(leftover)
        assert not leftover
        assert worker.storm_solves >= 1, "storm solve never ran"
        _drain_residuals(server, worker, chain_jobs + fam_jobs)
        _assert_zero_lost(server, fam_jobs)
        storm_placed = _placements(server, fam_jobs)
        _assert_same_everywhere("storm placements", storm_placed)
        result["storm"] = {
            "members": n_family,
            "solves": worker.storm_solves,
            "fallbacks": worker.storm_fallbacks,
            "placements": len(storm_placed),
            "solve_wall_s": round(
                worker.timings["storm_solve"], 4
            ),
        }

        # -- phase: kernel A/B — sharded == single-device, timed ------
        from nomad_tpu.ops.solve import (
            storm_assignment,
            storm_assignment_sharded,
        )
        from nomad_tpu.parallel.mesh import mesh_put
        from nomad_tpu.sched.storm import stage_for_mesh
        from jax.sharding import PartitionSpec as P

        dtype = np.asarray(table.cpu_total).dtype
        inp, cols = _kernel_storm_problem(
            KERNEL_E, KERNEL_A, KERNEL_C, dtype
        )
        single = storm_assignment(
            inp, cols, spread_fit=False, max_rounds=KERNEL_A
        )
        single = tuple(np.asarray(x) for x in single)

        fn = storm_assignment_sharded(
            mesh, spread_fit=False, max_rounds=KERNEL_A
        )
        s_inp = stage_for_mesh(inp, mesh)
        s_cols = tuple(
            mesh_put(mesh, c, P("nodes")) for c in cols
        )
        sharded = tuple(
            np.asarray(x) for x in fn(s_inp, s_cols)
        )
        for name, a, b in zip(
            ("assigned", "pulls", "acc_round", "score", "greedy",
             "rounds"),
            single, sharded,
        ):
            assert np.array_equal(a, b), (
                f"sharded storm diverged from single-device in "
                f"{name}"
            )
        def best_of(f, n=3):
            best = float("inf")
            for _ in range(n):
                t = time.monotonic()
                jax.block_until_ready(f())
                best = min(best, time.monotonic() - t)
            return best

        t_single = best_of(
            lambda: storm_assignment(
                inp, cols, spread_fit=False, max_rounds=KERNEL_A
            )
        )
        t_sharded = best_of(lambda: fn(s_inp, s_cols))
        result["storm_kernel"] = {
            "rows": KERNEL_A,
            "arena": KERNEL_C,
            "rounds": int(single[5]),
            "bit_identical": True,
            "single_device_ms": round(t_single * 1000.0, 2),
            "sharded_ms": round(t_sharded * 1000.0, 2),
        }
        _assert_same_everywhere(
            "kernel assignment", sharded[0].tolist()
        )
        result["cross_host_parity"] = True
        result["zero_lost"] = True
    finally:
        server.stop()
    if rank == 0:
        print("DIST_SMOKE_JSON " + json.dumps(result), flush=True)
    return 0


# ---------------------------------------------------------------------------
# launcher
# ---------------------------------------------------------------------------


def launch(
    procs: int = 2,
    devices_per_proc: int = DEVICES_PER_PROC,
    timeout: float = 420.0,
    extra_env: Optional[dict] = None,
) -> dict:
    """Spawn the distributed smoke and return rank 0's result row.
    Raises RuntimeError (with the children's log tails) on failure or
    timeout — a collective deadlock must fail the gate, not hang it."""
    import tempfile

    from ..device_lock import scrub_accelerator_env

    port = _free_port()
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    log_dir = tempfile.mkdtemp(prefix="dist_smoke_")
    children: List[subprocess.Popen] = []
    outs = []
    for rank in range(procs):
        env = scrub_accelerator_env()
        # hermetic world: the parent shell's NOMAD_TPU_* knobs must
        # not reshape (or fail) the deterministic gate — children see
        # ONLY the pinned knob set below
        for key in [k for k in env if k.startswith("NOMAD_TPU_")]:
            del env[key]
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "XLA_FLAGS": (
                    "--xla_force_host_platform_device_count="
                    f"{devices_per_proc}"
                ),
                "NOMAD_TPU_DIST": "1",
                "NOMAD_TPU_DIST_COORD": f"127.0.0.1:{port}",
                "NOMAD_TPU_DIST_PROCS": str(procs),
                "NOMAD_TPU_DIST_ID": str(rank),
                "NOMAD_TPU_MESH": "1",
                "NOMAD_TPU_STORM": "1",
                "NOMAD_TPU_STORM_MIN": "8",
                # lockstep determinism: no timing-dependent admission
                # or width planning, compiles block inline
                "NOMAD_TPU_ADMIT": "0",
                "NOMAD_TPU_LATENCY_BUDGET_MS": "0",
                "NOMAD_TPU_SYNC_COMPILE": "1",
                "NOMAD_TPU_BROKER_WATCHDOG": "1",
            }
        )
        if extra_env:
            env.update(extra_env)
        out = open(
            os.path.join(log_dir, f"p{rank}.log"), "w+"
        )
        outs.append(out)
        children.append(
            subprocess.Popen(
                [
                    sys.executable, "-m",
                    "nomad_tpu.parallel.dist_smoke", "--worker",
                ],
                env=env,
                cwd=repo_root,
                stdout=out,
                stderr=subprocess.STDOUT,
            )
        )
    deadline = time.monotonic() + timeout
    rcs: List[Optional[int]] = [None] * procs
    while time.monotonic() < deadline and any(
        rc is None for rc in rcs
    ):
        for i, child in enumerate(children):
            if rcs[i] is None:
                rcs[i] = child.poll()
        time.sleep(0.2)
    for child in children:
        if child.poll() is None:
            child.kill()
    for child in children:
        # reap before reading tails: a SIGKILL'd child's buffered
        # output may not have landed yet, and an unreaped child
        # lingers as a zombie in long-lived bench/pytest parents
        try:
            child.wait(timeout=10)
        except Exception:  # noqa: BLE001 — diagnostics best-effort
            pass
    tails = []
    for rank, out in enumerate(outs):
        out.seek(0)
        tails.append((rank, out.read()))
        out.close()
    if any(rc != 0 for rc in rcs):
        detail = "\n".join(
            f"--- rank {rank} (rc={rcs[rank]}) ---\n{tail[-3000:]}"
            for rank, tail in tails
        )
        raise RuntimeError(
            f"distributed smoke failed (rcs={rcs}, "
            f"timeout={'yes' if None in rcs else 'no'}, "
            f"logs in {log_dir}):\n{detail}"
        )
    for line in tails[0][1].splitlines():
        if line.startswith("DIST_SMOKE_JSON "):
            return json.loads(line[len("DIST_SMOKE_JSON "):])
    raise RuntimeError(
        "distributed smoke exited clean but emitted no result row"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="multi-host mesh smoke (spawned CPU processes)"
    )
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--procs", type=int, default=2)
    parser.add_argument(
        "--devices-per-proc", type=int, default=DEVICES_PER_PROC
    )
    parser.add_argument("--timeout", type=float, default=420.0)
    args = parser.parse_args(argv)
    if args.worker:
        return run_worker()
    result = launch(
        procs=args.procs,
        devices_per_proc=args.devices_per_proc,
        timeout=args.timeout,
    )
    print(json.dumps(result, indent=2))
    print(
        f"dist_smoke: OK — {result['procs']} processes x "
        f"{result['devices_per_host']} devices, zero lost, "
        "cross-host parity held"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
