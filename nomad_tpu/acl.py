"""ACL system (reference acl/acl.go:43, acl/policy.go, nomad/acl.go).

Policies grant namespace capabilities plus coarse node/agent/operator/
quota rights; tokens reference policies; management tokens bypass all
checks.  Resolution (token -> merged ACL object) is cached with the same
intent as the reference's LRU (nomad/server.go:89 aclCacheSize).

Policy JSON shape (HCL in the reference; JSON here):

    {
      "namespaces": {
        "default": {"policy": "write"},
        "web-*":   {"capabilities": ["submit-job", "read-job"]}
      },
      "node": "write",
      "agent": "read",
      "operator": "read",
      "quota": "read"
    }
"""
from __future__ import annotations

import fnmatch
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .structs import new_id

# namespace capability sets (reference acl/policy.go:19-60)
NAMESPACE_CAPABILITIES = {
    "deny": set(),
    "read": {
        "list-jobs",
        "read-job",
        "read-logs",
        "read-fs",
        "read-job-scaling",
        "list-scaling-policies",
        "read-scaling-policy",
        "csi-list-volume",
        "csi-read-volume",
    },
    "write": {
        "list-jobs",
        "read-job",
        "submit-job",
        "dispatch-job",
        "read-logs",
        "read-fs",
        "alloc-exec",
        "alloc-lifecycle",
        "scale-job",
        "read-job-scaling",
        "list-scaling-policies",
        "read-scaling-policy",
        "csi-list-volume",
        "csi-read-volume",
        "csi-write-volume",
        "csi-mount-volume",
    },
}

COARSE_POLICIES = ("deny", "read", "write")


@dataclass
class NamespacePolicy:
    policy: str = ""  # deny | read | write
    capabilities: Set[str] = field(default_factory=set)

    def allowed(self) -> Set[str]:
        caps = set(self.capabilities)
        if self.policy:
            caps |= NAMESPACE_CAPABILITIES.get(self.policy, set())
        if self.policy == "deny":
            return set()
        return caps


@dataclass
class Policy:
    name: str = ""
    namespaces: Dict[str, NamespacePolicy] = field(default_factory=dict)
    node: str = ""  # deny | read | write
    agent: str = ""
    operator: str = ""
    quota: str = ""

    @classmethod
    def from_dict(cls, name: str, raw: Dict) -> "Policy":
        namespaces = {}
        for ns, rules in (raw.get("namespaces") or {}).items():
            namespaces[ns] = NamespacePolicy(
                policy=rules.get("policy", ""),
                capabilities=set(rules.get("capabilities") or ()),
            )
        return cls(
            name=name,
            namespaces=namespaces,
            node=raw.get("node", ""),
            agent=raw.get("agent", ""),
            operator=raw.get("operator", ""),
            quota=raw.get("quota", ""),
        )


@dataclass
class Token:
    accessor_id: str = field(default_factory=new_id)
    secret_id: str = field(default_factory=new_id)
    name: str = ""
    type: str = "client"  # client | management
    policies: List[str] = field(default_factory=list)
    global_: bool = False

    def is_management(self) -> bool:
        return self.type == "management"


class ACL:
    """A merged capability view over a set of policies
    (reference acl/acl.go:43)."""

    def __init__(self, policies: List[Policy], management: bool = False):
        self.management = management
        self.policies = policies

    def _namespace_caps(self, namespace: str) -> Set[str]:
        caps: Set[str] = set()
        denied = False
        for policy in self.policies:
            # exact match beats glob (reference acl.go findClosestMatching)
            exact = policy.namespaces.get(namespace)
            matched = exact
            if matched is None:
                best_len = -1
                for pattern, ns_policy in policy.namespaces.items():
                    if fnmatch.fnmatchcase(namespace, pattern):
                        if len(pattern) > best_len:
                            best_len = len(pattern)
                            matched = ns_policy
            if matched is None:
                continue
            if matched.policy == "deny":
                denied = True
            caps |= matched.allowed()
        return set() if denied and not caps else caps

    def allow_namespace_operation(
        self, namespace: str, capability: str
    ) -> bool:
        if self.management:
            return True
        return capability in self._namespace_caps(namespace)

    def _coarse(self, attr: str, write: bool) -> bool:
        if self.management:
            return True
        level = "deny"
        for policy in self.policies:
            value = getattr(policy, attr)
            if value == "write":
                level = "write"
            elif value == "read" and level != "write":
                level = "read"
        if write:
            return level == "write"
        return level in ("read", "write")

    def allow_node_read(self) -> bool:
        return self._coarse("node", write=False)

    def allow_node_write(self) -> bool:
        return self._coarse("node", write=True)

    def allow_agent_read(self) -> bool:
        return self._coarse("agent", write=False)

    def allow_agent_write(self) -> bool:
        return self._coarse("agent", write=True)

    def allow_operator_read(self) -> bool:
        return self._coarse("operator", write=False)

    def allow_operator_write(self) -> bool:
        return self._coarse("operator", write=True)


class ACLStore:
    """Token/policy storage + resolution cache
    (reference nomad/acl.go ResolveToken)."""

    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self.policies: Dict[str, Policy] = {}
        self.tokens_by_secret: Dict[str, Token] = {}
        self.tokens_by_accessor: Dict[str, Token] = {}
        self._cache: Dict[str, ACL] = {}

    # -- management -----------------------------------------------------

    def bootstrap(self) -> Token:
        """Create the initial management token
        (reference acl_endpoint.go Bootstrap)."""
        token = Token(name="Bootstrap Token", type="management")
        with self._lock:
            self.tokens_by_secret[token.secret_id] = token
            self.tokens_by_accessor[token.accessor_id] = token
        return token

    def upsert_policy(self, policy: Policy) -> None:
        with self._lock:
            self.policies[policy.name] = policy
            self._cache.clear()

    def delete_policy(self, name: str) -> None:
        with self._lock:
            self.policies.pop(name, None)
            self._cache.clear()

    def create_token(self, token: Token) -> Token:
        with self._lock:
            for p in token.policies:
                if p not in self.policies:
                    raise ValueError(f"unknown policy {p!r}")
            self.tokens_by_secret[token.secret_id] = token
            self.tokens_by_accessor[token.accessor_id] = token
            # upsert path: a token update must drop the cached ACL or
            # stripped policies keep being honored until restart
            self._cache.pop(token.secret_id, None)
        return token

    def delete_token(self, accessor_id: str) -> None:
        with self._lock:
            token = self.tokens_by_accessor.pop(accessor_id, None)
            if token is not None:
                self.tokens_by_secret.pop(token.secret_id, None)
                self._cache.pop(token.secret_id, None)

    # -- resolution -----------------------------------------------------

    def resolve(self, secret_id: str) -> Optional[ACL]:
        if not secret_id:
            return ACL([], management=False)
        with self._lock:
            cached = self._cache.get(secret_id)
            if cached is not None:
                return cached
            token = self.tokens_by_secret.get(secret_id)
            if token is None:
                return None
            acl = ACL(
                [
                    self.policies[p]
                    for p in token.policies
                    if p in self.policies
                ],
                management=token.is_management(),
            )
            self._cache[secret_id] = acl
            return acl

    def allowed(
        self, secret_id: str, namespace: str, capability: str
    ) -> bool:
        """Route-level check used by the HTTP layer.  Capability forms:
        "submit-job" (namespace capability), "node:read"/"node:write",
        "agent:...", "operator:...".
        """
        acl = self.resolve(secret_id)
        if acl is None:
            return False
        if ":" in capability:
            scope, mode = capability.split(":", 1)
            method = getattr(acl, f"allow_{scope}_{mode}", None)
            return bool(method and method())
        return acl.allow_namespace_operation(namespace, capability)
