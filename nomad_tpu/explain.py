"""Placement explainability: per-eval score decomposition + filter
attribution for the vectorized scheduling path.

The TPU kernel path collapses Nomad's per-node ranking loop into a dense
score matrix — fast, but it threw away the *why*: which nodes were
masked (and by which constraint), which dimensions were exhausted, and
how the winner's normalized score decomposes into its terms.  This
module is the retention + vocabulary half of the layer:

* **Reason vocabulary.**  Every filter reason the vectorized path
  attributes maps onto a fixed slug set (``reason_slug`` /
  ``dimension_slug``) shared with the serial iterator chain's strings
  (sched/feasible.py FILTER_CONSTRAINT_*), so dashboards key on a
  bounded family of ``placement.filtered.<slug>`` counters instead of
  unbounded ad-hoc strings.  ``tools/check_stage_accounting.py`` lints
  both sides: emitted ``placement.*`` names must appear in the
  registries below (zero-registered at server construction), and the
  vectorized path's reason literals must come from the shared
  constants.

* **Retention ring.**  One process-wide ring of ``EXPLAIN_RING``
  per-eval placement explanations (mirroring the trace ring's
  retention discipline: newest-wins per eval id, bounded, O(1)
  appends), keyed by eval id and cross-linked with the flight
  recorder: the explanation carries the trace id and the trace is
  annotated with the placement ref, so a ``/v1/traces/<eval_id>``
  waterfall and its ``/v1/evaluation/<eval_id>/placement`` breakdown
  reference each other.

* **Opt-out, not opt-in.**  ``NOMAD_TPU_EXPLAIN=0`` turns capture and
  recording into no-ops (``EXPLAIN.set_enabled`` flips it at runtime
  for the bench's A/B overhead gate).

Explanations are recorded for *every* eval the schedulers complete —
successful placements included — not just failed ones: debugging a
*suspicious* placement is the common case (Narayanan et al., OSDI'20;
Tesserae), and by then the eval already succeeded.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .sched.feasible import (
    FILTER_CLASS_INELIGIBLE,
    FILTER_CONSTRAINT_CSI_VOLUMES,
    FILTER_CONSTRAINT_DEVICES,
    FILTER_CONSTRAINT_DRIVERS,
    FILTER_CONSTRAINT_HOST_VOLUMES,
    FILTER_CONSTRAINT_NETWORK,
)
from .structs import CONSTRAINT_DISTINCT_HOSTS

# retained explanations (completed evals); an explanation is a few KB
# (top-K score meta + reason histograms), so the ring stays near 4 MB
EXPLAIN_RING = 1024

# fixed slug vocabulary for placement.filtered.<slug> counters: every
# reason string the stacks attribute folds into exactly one of these
PLACEMENT_FILTER_SLUGS = (
    "constraint",
    "class-ineligible",
    "missing-drivers",
    "missing-devices",
    "missing-host-volumes",
    "missing-csi-plugins",
    "missing-network",
    "distinct-hosts",
    "distinct-property",
    "other",
)

# fixed slug vocabulary for placement.exhausted.<slug> counters
# (BinPackIterator / allocs_fit dimension strings)
PLACEMENT_EXHAUST_SLUGS = (
    "cpu",
    "memory",
    "disk",
    "ports",
    "bandwidth",
    "devices",
    "other",
)

# the full zero-registered placement.* metric families; the server
# preregisters these at construction so prometheus scrapes export the
# whole family before the first eval (absence-of-series must mean
# absence-of-filtering, never "not emitted yet")
PLACEMENT_COUNTERS = (
    ("placement.explained",)
    + tuple(f"placement.filtered.{s}" for s in PLACEMENT_FILTER_SLUGS)
    + tuple(f"placement.exhausted.{s}" for s in PLACEMENT_EXHAUST_SLUGS)
)
PLACEMENT_GAUGES = (
    "placement.score_spread",
    "placement.winner_margin",
)


def reason_slug(reason: str) -> str:
    """Fold a filter-reason string (serial-chain vocabulary) into its
    fixed counter slug."""
    if reason == FILTER_CLASS_INELIGIBLE:
        return "class-ineligible"
    if reason == FILTER_CONSTRAINT_DRIVERS:
        return "missing-drivers"
    if reason == FILTER_CONSTRAINT_DEVICES:
        return "missing-devices"
    if reason == FILTER_CONSTRAINT_HOST_VOLUMES:
        return "missing-host-volumes"
    if reason == FILTER_CONSTRAINT_CSI_VOLUMES:
        return "missing-csi-plugins"
    if reason == FILTER_CONSTRAINT_NETWORK:
        return "missing-network"
    if reason == CONSTRAINT_DISTINCT_HOSTS:
        return "distinct-hosts"
    if reason.startswith("distinct_property") or reason.startswith(
        "missing property"
    ):
        return "distinct-property"
    # "<ltarget> <operand> <rtarget>" — every remaining serial-chain
    # reason is a concrete constraint rendering
    if " " in reason:
        return "constraint"
    return "other"


def dimension_slug(dimension: str) -> str:
    """Fold an exhaustion-dimension string (allocs_fit / binpack
    vocabulary) into its fixed counter slug."""
    if dimension in ("cpu", "memory", "disk"):
        return dimension
    if "port" in dimension:
        return "ports"
    if "device" in dimension:
        return "devices"
    if "bandwidth" in dimension:
        return "bandwidth"
    return "other"


def preregister(metrics) -> None:
    """Zero-register the placement.* families on a telemetry store."""
    metrics.preregister(
        counters=PLACEMENT_COUNTERS, gauges=PLACEMENT_GAUGES
    )


def alloc_metric_to_api(metric, winner_node_id: str = "") -> Dict:
    """Full Nomad-API-shaped AllocMetric payload (ScoreMetaData trimmed
    to top-K on this read, winner always retained)."""
    return {
        "NodesEvaluated": metric.nodes_evaluated,
        "NodesFiltered": metric.nodes_filtered,
        "NodesAvailable": dict(metric.nodes_available),
        "ClassFiltered": dict(metric.class_filtered),
        "ConstraintFiltered": dict(metric.constraint_filtered),
        "NodesExhausted": metric.nodes_exhausted,
        "ClassExhausted": dict(metric.class_exhausted),
        "DimensionExhausted": dict(metric.dimension_exhausted),
        "QuotaExhausted": list(metric.quota_exhausted),
        "ScoreMetaData": [
            {
                "NodeID": m.node_id,
                "Scores": dict(m.scores),
                "NormScore": m.norm_score,
            }
            for m in metric.top_score_meta(
                winner_node_id=winner_node_id
            )
        ],
        "AllocationTime": metric.allocation_time_s,
        "CoalescedFailures": metric.coalesced_failures,
    }


class ExplainRecorder:
    """Bounded per-eval placement-explanation store (trace-ring
    retention discipline: deque ring + newest-per-eval-id index)."""

    def __init__(self, ring: int = EXPLAIN_RING) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._ring_cap = ring
        self._by_id: Dict[str, Dict] = {}
        self.enabled = os.environ.get("NOMAD_TPU_EXPLAIN", "1") != "0"

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    # -- building ------------------------------------------------------

    def build_record(self, ev, scheduler) -> Optional[Dict]:
        """Assemble one eval's placement explanation from a completed
        scheduler run: per-TG winner + full AllocMetric breakdown for
        placed groups, failed-TG metrics for the rest.  Returns None
        when disabled or the run produced nothing explainable."""
        if not self.enabled:
            return None
        plan = getattr(scheduler, "plan", None)
        failed = getattr(scheduler, "failed_tg_allocs", None) or {}
        groups: Dict[str, Dict] = {}
        if plan is not None:
            for allocs in plan.node_allocation.values():
                for alloc in allocs:
                    if alloc.eval_id != ev.id or alloc.metrics is None:
                        continue
                    g = groups.setdefault(
                        alloc.task_group,
                        {"placed": 0, "placements": []},
                    )
                    g["placed"] += 1
                    g["placements"].append(
                        {
                            "Name": alloc.name,
                            "NodeID": alloc.node_id,
                            "NodeName": alloc.node_name,
                            "NormScore": (
                                alloc.metrics.node_norm_score(
                                    alloc.node_id
                                )
                            ),
                        }
                    )
                    # the group's freshest full breakdown: highest
                    # select sequence wins (plan collections iterate
                    # in node-insertion order, which is NOT placement
                    # order; earlier metrics stay reachable via the
                    # per-alloc API)
                    prior = g.get("metric")
                    if (
                        prior is None
                        or alloc.metrics.seq >= prior.seq
                    ):
                        g["metric"] = alloc.metrics
                        g["winner"] = alloc.node_id
        for tg, metric in failed.items():
            g = groups.setdefault(tg, {"placed": 0, "placements": []})
            g["failed"] = True
            g["metric"] = metric
            g.setdefault("winner", "")
        if not groups:
            return None
        from .trace import TRACE

        task_groups = {}
        for tg, g in groups.items():
            metric = g.get("metric")
            entry = {
                "Placed": g["placed"],
                "Failed": bool(g.get("failed")),
                "Winner": g.get("winner", ""),
                "Placements": g["placements"],
                "Metric": (
                    alloc_metric_to_api(
                        metric, winner_node_id=g.get("winner", "")
                    )
                    if metric is not None
                    else None
                ),
            }
            if metric is not None:
                # bin-pack imbalance over the UNTRIMMED score meta —
                # the serialized ScoreMetaData is top-K and would
                # measure only the spread among the best few nodes
                norms = sorted(
                    (m.norm_score for m in metric.score_meta),
                    reverse=True,
                )
                if len(norms) >= 2:
                    entry["ScoreSpread"] = norms[0] - norms[-1]
                    entry["WinnerMargin"] = norms[0] - norms[1]
            task_groups[tg] = entry
        return {
            "EvalID": ev.id,
            "JobID": ev.job_id,
            "Namespace": ev.namespace,
            "Type": ev.type,
            "TriggeredBy": ev.triggered_by,
            "TraceID": TRACE.trace_id_of(ev.id),
            "RecordedAt": time.time(),
            "TaskGroups": task_groups,
        }

    # -- recording -----------------------------------------------------

    def publish(self, record: Optional[Dict], metrics=None) -> None:
        """Retain a built record and emit its cluster-shape telemetry.
        Accepts None (disabled / nothing explainable) so call sites
        stay one line."""
        if record is None or not self.enabled:
            return
        eval_id = record["EvalID"]
        with self._lock:
            prior = self._by_id.get(eval_id)
            if prior is not None:
                # newest-wins per eval id: a redelivered eval's stale
                # explanation must not linger in /v1/placements next
                # to its replacement
                try:
                    self._ring.remove(prior)
                except ValueError:
                    pass
            self._by_id[eval_id] = record
            self._ring.append(record)
            while len(self._ring) > self._ring_cap:
                evicted = self._ring.popleft()
                if self._by_id.get(evicted["EvalID"]) is evicted:
                    del self._by_id[evicted["EvalID"]]
        # cross-link: the eval's trace now points at its explanation
        from .trace import TRACE

        TRACE.annotate(eval_id, placement=f"/v1/evaluation/{eval_id}/placement")
        if metrics is not None:
            self._emit(record, metrics)

    def annotate(self, eval_id: str, **fields) -> None:
        """Merge extra keys into an eval's retained record (no-op when
        the eval has no record — e.g. a discarded speculation).  The
        storm solver tags committed records with its round and
        assignment score this way, AFTER the commit decided which
        replay actually published."""
        if not self.enabled:
            return
        with self._lock:
            record = self._by_id.get(eval_id)
            if record is not None:
                record.update(fields)

    def record_eval(self, ev, scheduler, metrics=None) -> None:
        """build_record + publish in one call (the serial paths)."""
        if not self.enabled:
            return
        self.publish(self.build_record(ev, scheduler), metrics=metrics)

    def _emit(self, record: Dict, metrics) -> None:
        """Cluster-shape telemetry from one explanation: constraint
        pressure (``placement.filtered.<reason>`` /
        ``placement.exhausted.<dim>`` counters) and bin-pack imbalance
        (``placement.score_spread`` / ``placement.winner_margin``
        gauges) — trends dashboards can't see in latency metrics."""
        metrics.incr("placement.explained")
        for tg in record["TaskGroups"].values():
            m = tg.get("Metric")
            if m is None:
                continue
            for reason, n in m["ConstraintFiltered"].items():
                metrics.incr(
                    f"placement.filtered.{reason_slug(reason)}",
                    float(n),
                )
            for dim, n in m["DimensionExhausted"].items():
                metrics.incr(
                    f"placement.exhausted.{dimension_slug(dim)}",
                    float(n),
                )
            if "ScoreSpread" in tg:
                metrics.set_gauge(
                    "placement.score_spread", tg["ScoreSpread"]
                )
                metrics.set_gauge(
                    "placement.winner_margin", tg["WinnerMargin"]
                )

    # -- reads ---------------------------------------------------------

    def get(self, eval_id: str) -> Optional[Dict]:
        with self._lock:
            return self._by_id.get(eval_id)

    def recent(self, limit: int = 64) -> List[Dict]:
        with self._lock:
            candidates = list(self._ring)
        return list(reversed(candidates))[: max(1, limit)]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_id.clear()


EXPLAIN = ExplainRecorder()

__all__ = [
    "EXPLAIN",
    "EXPLAIN_RING",
    "ExplainRecorder",
    "FILTER_CLASS_INELIGIBLE",
    "FILTER_CONSTRAINT_NETWORK",
    "PLACEMENT_COUNTERS",
    "PLACEMENT_EXHAUST_SLUGS",
    "PLACEMENT_FILTER_SLUGS",
    "PLACEMENT_GAUGES",
    "alloc_metric_to_api",
    "dimension_slug",
    "preregister",
    "reason_slug",
]
