"""Framed-TCP transport: the cross-process counterpart of
InmemTransport.

One listener per registered local address serves every RPC family the
cluster multiplexes over a single port — raft (`request_vote`,
`append_entries`, `install_snapshot`), gossip (`gossip_*`), leader
forwarding (`fsm_apply`, `server_call`, `region_call`) — exactly the
reference's single-port design (nomad/rpc.go:250 multiplexes raft, RPC
and serf on one listener; nomad/raft_rpc.go layers raft on it).

Frames carry the wire codec from nomad_tpu/wire.py (shared with the
native library, byte-identical in C++ and Python), shaped as
``[method, src, payload]`` with an ``["ok", resp] | ["err", type,
detail, message]`` reply envelope, so typed errors — notably
NotLeaderError with its leader hint — survive the hop and follower
forwarding behaves identically in-process and across machines.

Failure behavior: dial/read timeouts raise TransportError fast, and a
circuit breaker remembers unreachable peers for a short window so the
leader's serial replication tick cannot stall behind one dead follower
(the reference gets the same property from per-follower replication
goroutines + pool timeouts, helper/pool/pool.go)."""
from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .. import wire
from .transport import TransportError

Handler = Callable[[str, dict], dict]


@dataclass
class TLSConfig:
    """Mutual-TLS material for the cluster transport (reference
    helper/tlsutil/config.go: verify_incoming + verify_outgoing with a
    shared CA — every server presents a cert and verifies its peer's).
    """

    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    # role-pinned server identity, e.g. "server.global.nomad"
    # (reference tlsutil verify_server_hostname): when set, outgoing
    # connections require the peer's cert to carry this name, so a
    # CA-signed CLIENT cert cannot impersonate a server.  Empty keeps
    # the r3 behavior: any CA-signed cert is a full cluster peer.
    server_name: str = ""

    def server_context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED  # verify_incoming
        return ctx

    def client_context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        # verify_outgoing: the CA + cert requirement authenticate the
        # peer; verify_server_hostname additionally pins the role name
        ctx.check_hostname = bool(self.server_name)
        ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

CONNECT_TIMEOUT = 0.5
CALL_TIMEOUT = 5.0
BREAKER_WINDOW = 1.0  # seconds an unreachable peer fails fast


def _not_leader_error():
    from .node import NotLeaderError

    return NotLeaderError


def _stale_leadership_error():
    # lazy: server.fsm imports this package (cycle at module level)
    from ..server.fsm import StaleLeadershipError

    return StaleLeadershipError


_ERR_TYPES = {
    "KeyError": KeyError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "TransportError": TransportError,
}


class TcpTransport:
    """InmemTransport-compatible transport over framed TCP sockets.

    Addresses are ``host:port`` strings.  A process typically registers
    ONE local address (its server) but the API allows several (tests).
    Client connections are pooled per destination and safe for
    concurrent use — each call checks a free connection out of the
    pool."""

    def __init__(self, tls: Optional[TLSConfig] = None) -> None:
        self._lock = threading.Lock()
        self._listeners: Dict[str, "_Listener"] = {}
        self._pools: Dict[str, List[socket.socket]] = {}
        self._breaker: Dict[str, float] = {}  # addr -> retry-after ts
        self.call_timeout = CALL_TIMEOUT
        self.tls = tls
        self._client_ctx = tls.client_context() if tls else None

    # -- server side ---------------------------------------------------

    def register(self, addr: str, handler: Handler) -> None:
        """Re-registering an address swaps the handler in place
        (ClusterServer registers raft, then gossip, then its combined
        dispatcher on the same port — with InmemTransport that's a dict
        overwrite, so the listener must survive re-registration)."""
        host, port = _split(addr)
        with self._lock:
            existing = self._listeners.get(addr)
            if existing is not None:
                existing.handler = handler
                return
        listener = _Listener(addr, host, port, handler, tls=self.tls)
        with self._lock:
            self._listeners[addr] = listener
        listener.start()

    def deregister(self, addr: str) -> None:
        with self._lock:
            listener = self._listeners.pop(addr, None)
        if listener is not None:
            listener.close()

    def close(self) -> None:
        with self._lock:
            listeners = list(self._listeners.values())
            self._listeners.clear()
            pools = list(self._pools.values())
            self._pools.clear()
        for listener in listeners:
            listener.close()
        for pool in pools:
            for sock in pool:
                try:
                    sock.close()
                except OSError:
                    pass

    # -- client side ---------------------------------------------------

    def rpc(self, src: str, dst: str, method: str, payload: dict) -> dict:
        now = time.monotonic()
        retry_after = self._breaker.get(dst, 0.0)
        if now < retry_after:
            raise TransportError(f"{dst} unreachable (breaker open)")
        frame = wire.encode([method, src, payload])  # before checkout:
        # an unencodable payload must not leak a pooled socket
        sock, pooled = self._checkout(dst)
        raw, err = self._exchange(sock, frame)
        if err is not None and pooled:
            # the pooled connection may simply be stale (peer
            # restarted); retry once on a fresh dial before declaring
            # the peer unreachable
            sock, _ = self._checkout(dst)
            raw, err = self._exchange(sock, frame)
        if err is not None:
            self._breaker[dst] = time.monotonic() + BREAKER_WINDOW
            raise TransportError(f"rpc to {dst} failed: {err}")
        self._checkin(dst, sock)
        reply = wire.decode(raw)
        if reply[0] == "ok":
            return reply[1]
        _kind, type_name, detail, message = reply
        if type_name == "StaleLeadershipError":
            # must survive the hop with its real type: the forwarding
            # retry loop treats it as DEFINITIVE (never re-forwarded),
            # and the worker layer's NotLeaderError handling converts
            # it to nack-for-redelivery — a bare RuntimeError would
            # take the generic crash path instead
            gen, fence = detail if detail else (0, 0)
            raise _stale_leadership_error()(gen, fence)
        if type_name == "NotLeaderError":
            raise _not_leader_error()(detail or None)
        exc_type = _ERR_TYPES.get(type_name, RuntimeError)
        raise exc_type(message)

    def _exchange(self, sock, frame):
        """One request/response on a connection; returns (raw, error).
        The socket is closed on any failure."""
        try:
            sock.settimeout(self.call_timeout)  # before send: a large
            # frame (install_snapshot) must not run under the short
            # connect timeout
            wire.send_frame(sock, frame)
            raw = wire.recv_frame(sock)
        except (OSError, ValueError) as exc:
            try:
                sock.close()
            except OSError:
                pass
            return None, exc
        if raw is None:
            try:
                sock.close()
            except OSError:
                pass
            return None, ConnectionError("connection closed mid-call")
        return raw, None

    def _checkout(self, dst: str):
        """Returns (socket, came_from_pool)."""
        with self._lock:
            pool = self._pools.get(dst)
            if pool:
                return pool.pop(), True
        host, port = _split(dst)
        try:
            sock = socket.create_connection(
                (host, port), timeout=CONNECT_TIMEOUT
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._client_ctx is not None:
                sock = self._client_ctx.wrap_socket(
                    sock,
                    server_hostname=(
                        self.tls.server_name
                        if self.tls and self.tls.server_name
                        else None
                    ),
                )
        except OSError as exc:
            self._breaker[dst] = time.monotonic() + BREAKER_WINDOW
            raise TransportError(f"dial {dst} failed: {exc}") from exc
        self._breaker.pop(dst, None)
        return sock, False

    def _checkin(self, dst: str, sock: socket.socket) -> None:
        self._breaker.pop(dst, None)
        with self._lock:
            pool = self._pools.setdefault(dst, [])
            if len(pool) < 8:
                pool.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass


class _Listener:
    def __init__(
        self,
        addr: str,
        host: str,
        port: int,
        handler: Handler,
        tls: Optional[TLSConfig] = None,
    ) -> None:
        self.addr = addr
        self.handler = handler
        self._server_ctx = tls.server_context() if tls else None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conn_lock = threading.Lock()

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def start(self) -> None:
        t = threading.Thread(
            target=self._accept_loop,
            name=f"tcp-accept-{self.addr}",
            daemon=True,
        )
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        """Closes the accept socket AND every live accepted connection,
        so the port is actually re-bindable afterwards and no serve
        thread stays parked in recv forever."""
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _peer = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._server_ctx is not None:
                # handshake on the serve thread, not here: a client
                # that never handshakes must not stall the accept loop
                t = threading.Thread(
                    target=self._serve_tls,
                    args=(conn,),
                    name=f"tcp-tls-{self.addr}",
                    daemon=True,
                )
                t.start()
                continue
            if not self._track(conn):
                continue
            t = threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name=f"tcp-conn-{self.addr}",
                daemon=True,
            )
            t.start()

    def _serve_tls(self, raw_conn: socket.socket) -> None:
        import ssl

        try:
            raw_conn.settimeout(5.0)
            conn = self._server_ctx.wrap_socket(
                raw_conn, server_side=True
            )
            conn.settimeout(None)
        except (ssl.SSLError, OSError):
            # bad cert / plaintext client: drop it
            try:
                raw_conn.close()
            except OSError:
                pass
            return
        if not self._track(conn):
            return
        self._serve_conn(conn)

    def _track(self, conn: socket.socket) -> bool:
        """Register a live connection, or close it when the listener
        already shut down — the append must never race past close()'s
        sweep (the TLS handshake widens that window to seconds)."""
        with self._conn_lock:
            if not self._stop.is_set():
                self._conns.append(conn)
                return True
        try:
            conn.close()
        except OSError:
            pass
        return False

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                raw = wire.recv_frame(conn)
                if raw is None:
                    return
                method, _src, payload = wire.decode(raw)
                try:
                    resp = self.handler(method, payload)
                    reply = ["ok", resp]
                except Exception as exc:  # noqa: BLE001 — typed envelope
                    reply = _error_envelope(exc)
                try:
                    out = wire.encode(reply)
                except TypeError as exc:
                    # a handler returned a non-wire-safe value; answer
                    # with an error envelope instead of killing the
                    # connection (which would stall the caller for the
                    # whole call timeout)
                    out = wire.encode(_error_envelope(exc))
                wire.send_frame(conn, out)
        except (OSError, ValueError):
            pass
        finally:
            with self._conn_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
            try:
                conn.close()
            except OSError:
                pass


def _error_envelope(exc: Exception) -> list:
    type_name = type(exc).__name__
    detail = None
    if type_name == "StaleLeadershipError":
        detail = [
            getattr(exc, "gen", 0), getattr(exc, "fence", 0),
        ]
    elif type_name == "NotLeaderError":
        detail = getattr(exc, "leader", None)
    return ["err", type_name, detail, str(exc)]


def _split(addr: str) -> Tuple[str, int]:
    host, _sep, port = addr.rpartition(":")
    if not host:
        raise ValueError(f"address {addr!r} is not host:port")
    return host, int(port)
