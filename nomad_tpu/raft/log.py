"""Replicated log storage (reference: raft-boltdb log store,
nomad/server.go:105-109).

In-memory list with a compaction offset; the snapshot path truncates the
prefix once the FSM has captured state through an index.  Entries are
(index, term, kind, data) where data is an opaque serialized command —
the raft core never interprets it (reference fsm.go owns decode).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

KIND_COMMAND = 0
KIND_NOOP = 1  # barrier entry appended on leadership (raft LogNoop)
KIND_CONFIG = 2  # membership change (raft LogConfiguration)


@dataclass
class LogEntry:
    index: int
    term: int
    kind: int = KIND_COMMAND
    data: bytes = b""


class RaftLog:
    """Compactable in-memory log.  Index 0 is the null sentinel; the
    first real entry has index 1 (matching hashicorp/raft)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: List[LogEntry] = []
        # index/term of the entry just before self._entries[0]
        self._snapshot_index = 0
        self._snapshot_term = 0

    # -- reads ----------------------------------------------------------

    def last_index(self) -> int:
        with self._lock:
            if self._entries:
                return self._entries[-1].index
            return self._snapshot_index

    def last_term(self) -> int:
        with self._lock:
            if self._entries:
                return self._entries[-1].term
            return self._snapshot_term

    def term_at(self, index: int) -> Optional[int]:
        """Term of the entry at index, or None if not present (compacted
        away or beyond the end).  Index 0 always has term 0."""
        with self._lock:
            if index == 0:
                return 0
            if index == self._snapshot_index:
                return self._snapshot_term
            entry = self._get(index)
            return entry.term if entry is not None else None

    def _get(self, index: int) -> Optional[LogEntry]:
        pos = index - self._snapshot_index - 1
        if 0 <= pos < len(self._entries):
            return self._entries[pos]
        return None

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            return self._get(index)

    def entries_from(self, index: int, limit: int = 512) -> List[LogEntry]:
        """Entries with log index >= index (up to limit)."""
        with self._lock:
            pos = index - self._snapshot_index - 1
            if pos < 0:
                return []  # compacted; caller must fall back to snapshot
            return list(self._entries[pos : pos + limit])

    @property
    def snapshot_index(self) -> int:
        with self._lock:
            return self._snapshot_index

    @property
    def snapshot_term(self) -> int:
        with self._lock:
            return self._snapshot_term

    # -- writes ---------------------------------------------------------

    def append(self, entry: LogEntry) -> None:
        with self._lock:
            assert entry.index == self.last_index() + 1
            self._entries.append(entry)

    def truncate_from(self, index: int) -> None:
        """Drop entries with log index >= index (conflict resolution,
        AppendEntries receiver step 3)."""
        with self._lock:
            pos = index - self._snapshot_index - 1
            if pos < len(self._entries):
                del self._entries[max(pos, 0) :]

    def compact_through(self, index: int, term: int) -> None:
        """Discard entries with log index <= index after an FSM snapshot
        covers them."""
        with self._lock:
            if index <= self._snapshot_index:
                return
            keep = index - self._snapshot_index
            del self._entries[:keep]
            self._snapshot_index = index
            self._snapshot_term = term

    def reset_to_snapshot(self, index: int, term: int) -> None:
        """Discard the whole log after installing a snapshot."""
        with self._lock:
            self._entries.clear()
            self._snapshot_index = index
            self._snapshot_term = term
