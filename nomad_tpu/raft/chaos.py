"""Deterministic cluster fault injection (the control-plane sibling of
``nomad_tpu/device/faults.py``).

Two layers, built for reproducibility:

* **ChaosTransport** — an :class:`InmemTransport` with a seeded fault
  plan: probabilistic message drops (``msg_drop[:pct]``), per-RPC wire
  delay (``slow_wire[:ms]``), and named partitions
  (``partition[:a,b]`` splits the listed addresses from everyone
  else).  Drop decisions come from a per-(src, dst) RNG stream
  derived from the seed, so each link's drop sequence is
  deterministic and independent of unrelated links' traffic —
  thread scheduling can still vary WHICH high-level operation lands
  on a given draw, so replays are per-link-deterministic, not
  whole-cluster bit-for-bit.  ``NOMAD_TPU_CLUSTER_FAULT`` arms a plan process-wide the way
  ``NOMAD_TPU_FAULT`` arms device faults; the chaos smoke and tests
  also arm plans programmatically.  ``leader_kill`` is a schedule
  directive (the harness isolates/kills whoever currently leads — the
  transport cannot know that), parsed here so one knob names every
  fault class.

* **race hooks** — named synchronization points the batched hot path
  fires at its leadership-sensitive seams (``storm_staged``,
  ``storm_solved``, ``pre_commit_wave``, ``chunk_launched``).  A test
  installs a callable to force a revoke at EXACTLY that seam —
  deterministic leadership-loss races without monkeypatching pipeline
  internals.  Unarmed hooks are a dict lookup on an empty dict:
  nothing on the hot path gets slower.
"""
from __future__ import annotations

import hashlib
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .transport import InmemTransport, TransportError

# -- race hooks ---------------------------------------------------------

_HOOKS: Dict[str, Callable[[], None]] = {}
_HOOKS_LOCK = threading.Lock()


def install_hook(name: str, fn: Callable[[], None]) -> None:
    """Arm a race hook (test-only; see module docstring)."""
    with _HOOKS_LOCK:
        _HOOKS[name] = fn


def clear_hooks() -> None:
    with _HOOKS_LOCK:
        _HOOKS.clear()


def fire(name: str) -> None:
    """Fire a named race hook if armed.  Hot-path cost when unarmed:
    one truthiness check on a module-level dict."""
    if not _HOOKS:
        return
    with _HOOKS_LOCK:
        fn = _HOOKS.get(name)
    if fn is not None:
        fn()


# -- fault plans --------------------------------------------------------


@dataclass
class Fault:
    """One parsed ``NOMAD_TPU_CLUSTER_FAULT`` directive."""

    kind: str  # leader_kill | partition | msg_drop | slow_wire
    members: List[str] = field(default_factory=list)  # partition
    pct: float = 0.0  # msg_drop
    ms: float = 0.0  # slow_wire


def parse_fault(spec: str) -> Optional[Fault]:
    """``leader_kill`` | ``partition[:a,b]`` | ``msg_drop[:pct]`` |
    ``slow_wire[:ms]`` -> Fault (None for empty/unknown specs —
    chaos must never break a production boot)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    kind, _, arg = spec.partition(":")
    kind = kind.strip()
    if kind == "leader_kill":
        return Fault(kind="leader_kill")
    if kind == "partition":
        members = [m.strip() for m in arg.split(",") if m.strip()]
        return Fault(kind="partition", members=members)
    if kind == "msg_drop":
        try:
            pct = float(arg) if arg else 5.0
        except ValueError:
            pct = 5.0
        return Fault(kind="msg_drop", pct=max(0.0, min(pct, 100.0)))
    if kind == "slow_wire":
        try:
            ms = float(arg) if arg else 5.0
        except ValueError:
            ms = 5.0
        return Fault(kind="slow_wire", ms=max(0.0, ms))
    return None


def armed_fault() -> Optional[Fault]:
    """The process-wide fault plan from ``NOMAD_TPU_CLUSTER_FAULT``
    (read per call: tests arm and disarm within one process)."""
    return parse_fault(os.environ.get("NOMAD_TPU_CLUSTER_FAULT", ""))


class ChaosTransport(InmemTransport):
    """InmemTransport with a deterministic, seeded fault plan.

    Faults apply to raft AND forwarding traffic (everything rides the
    same transport, like the reference's multiplexed RPC port), so a
    dropped forward or a slow append_entries exercises the identical
    recovery paths real hardware would."""

    def __init__(self, seed: int = 0) -> None:
        super().__init__()
        self._seed = seed
        # per-(src, dst) RNG streams: each link's drop sequence is a
        # pure function of (seed, src, dst, nth call on that link),
        # independent of every other link's traffic
        self._link_rngs: Dict[tuple, random.Random] = {}
        self._fault_lock = threading.Lock()
        self.drop_pct = 0.0
        self.delay_ms = 0.0
        self.delivered = 0
        self.dropped = 0

    # -- arming --------------------------------------------------------

    def arm(self, fault: Optional[Fault]) -> None:
        """Apply a parsed fault plan.  ``partition`` splits the named
        members from every other registered node; ``leader_kill`` is a
        harness directive and a no-op here."""
        if fault is None:
            return
        if fault.kind == "msg_drop":
            with self._fault_lock:
                self.drop_pct = fault.pct
        elif fault.kind == "slow_wire":
            with self._fault_lock:
                self.delay_ms = fault.ms
        elif fault.kind == "partition":
            self.partition_group(fault.members)

    def arm_from_env(self) -> None:
        self.arm(armed_fault())

    def disarm(self) -> None:
        with self._fault_lock:
            self.drop_pct = 0.0
            self.delay_ms = 0.0
        self.heal()

    def partition_group(self, members: List[str]) -> None:
        """Split ``members`` from every other registered address (both
        directions), leaving intra-group links up."""
        group = set(members)
        with self._lock:
            others = [a for a in self._handlers if a not in group]
        for m in members:
            for o in others:
                self.partition(m, o)

    # -- delivery ------------------------------------------------------

    def _link_rng(self, src: str, dst: str) -> random.Random:
        """Deterministic per-link stream (callers hold _fault_lock)."""
        key = (src, dst)
        rng = self._link_rngs.get(key)
        if rng is None:
            digest = hashlib.sha256(
                f"{self._seed}|{src}|{dst}".encode()
            ).digest()
            rng = random.Random(int.from_bytes(digest[:8], "big"))
            self._link_rngs[key] = rng
        return rng

    def rpc(self, src: str, dst: str, method: str, payload: dict) -> dict:
        with self._fault_lock:
            delay = self.delay_ms
            drop = (
                self.drop_pct
                and self._link_rng(src, dst).random() * 100.0
                < self.drop_pct
            )
        if delay:
            time.sleep(delay / 1000.0)
        if drop:
            self.dropped += 1
            raise TransportError(
                f"chaos: dropped {method} {src}->{dst}"
            )
        self.delivered += 1
        return super().rpc(src, dst, method, payload)
