"""Raft consensus core (leader election, log replication, commitment,
FSM apply, snapshot/compaction).

Plays the role hashicorp/raft plays for the reference server
(nomad/server.go:105 setupRaft, nomad/fsm.go Apply/Snapshot/Restore).
The FSM contract matches: apply(bytes) -> result for committed entries,
snapshot() -> bytes / restore(bytes) for compaction and catch-up.
Leadership changes surface through an observer callback, which the
server layer uses the way the reference uses the raft leaderCh
(nomad/leader.go:54 monitorLeadership -> establish/revokeLeadership).
"""
from __future__ import annotations

import pickle
import queue
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from .log import KIND_COMMAND, KIND_CONFIG, KIND_NOOP, LogEntry, RaftLog
from .transport import TransportError

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(Exception):
    def __init__(self, leader: Optional[str]) -> None:
        super().__init__(f"not the leader (leader hint: {leader})")
        self.leader = leader


class _Future:
    def __init__(self) -> None:
        self._event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None

    def resolve(self, result) -> None:
        self.result = result
        self._event.set()

    def fail(self, error: Exception) -> None:
        self.error = error
        self._event.set()

    def wait(self, timeout: float):
        if not self._event.wait(timeout):
            raise TimeoutError("raft apply timed out")
        if self.error is not None:
            raise self.error
        return self.result


class RaftNode:
    """One consensus participant.  Peers are a static configuration
    (the reference bootstraps from config/serf join;
    nomad/server.go:1355 bootstrapExpect)."""

    def __init__(
        self,
        addr: str,
        peers: List[str],
        transport,
        fsm,
        election_timeout: float = 0.15,
        heartbeat_interval: float = 0.04,
        snapshot_threshold: int = 2048,
        on_leadership: Optional[Callable[[bool, int], None]] = None,
    ) -> None:
        self.addr = addr
        self.peers = [p for p in peers if p != addr]
        self.transport = transport
        self.fsm = fsm
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.snapshot_threshold = snapshot_threshold
        self.on_leadership = on_leadership

        self._lock = threading.RLock()
        self.state = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log = RaftLog()
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: Optional[str] = None

        # leader volatile state
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._futures: Dict[int, _Future] = {}

        # retained FSM snapshot for follower catch-up
        self._snapshot_data: Optional[bytes] = None
        self._snapshot_config: Optional[List[str]] = None
        # newest config entry appended this leadership (None = none
        # pending; config changes chain off it, not the applied set)
        self._proposed_members: Optional[List[str]] = None
        self._removed = False  # this server was removed from the config

        self._deadline = 0.0  # election deadline (monotonic)
        self._wake = threading.Event()
        self._apply_cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._applied_since_snapshot = 0
        # ordered leadership notifications (the reference's raft
        # leaderCh is an ordered channel; firing callbacks from
        # detached threads could deliver up/down out of order)
        self._notify_q: "queue.Queue" = queue.Queue()

        transport.register(addr, self._handle_rpc)

    def _rpc(self, peer: str, method: str, payload: dict) -> dict:
        """Peer RPC with any failure normalized to TransportError, so
        a faulty peer can never crash the driver thread."""
        try:
            return self.transport.rpc(self.addr, peer, method, payload)
        except TransportError:
            raise
        except Exception as exc:  # noqa: BLE001
            raise TransportError(
                f"peer {peer} rpc {method} failed: {exc}"
            ) from exc

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._reset_election_deadline()
        for name, target in (
            ("raft-driver", self._driver),
            ("raft-apply", self._apply_loop),
            ("raft-notify", self._notify_loop),
        ):
            t = threading.Thread(
                target=target, name=f"{name}@{self.addr}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        with self._lock:
            was_leader = self.state == LEADER
            self.state = FOLLOWER
            for fut in self._futures.values():
                fut.fail(NotLeaderError(None))
            self._futures.clear()
            if was_leader:
                self._notify_q.put((False, self.current_term))
        self._notify_q.put(None)  # notifier drain sentinel
        self._stop.set()
        self._wake.set()
        with self._lock:
            self._apply_cv.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        self.transport.deregister(self.addr)

    def _notify_loop(self) -> None:
        """Single dispatcher so up/down events arrive in order."""
        while True:
            item = self._notify_q.get()
            if item is None:
                return
            if self.on_leadership:
                try:
                    self.on_leadership(*item)
                except Exception:  # noqa: BLE001 — observer fault
                    pass

    # -- public API -----------------------------------------------------

    def add_peer(self, addr: str) -> None:
        """Local membership change: add a voter (bootstrap/join seam;
        single-step config change, not joint consensus — safe here
        because changes are serialized through the leader).  For a
        running cluster, prefer add_server which commits the change
        through the replicated log."""
        with self._lock:
            if addr == self.addr or addr in self.peers:
                return
            self.peers.append(addr)
            if self.state == LEADER:
                self._next_index[addr] = self.log.last_index() + 1
                self._match_index[addr] = 0
        self._wake.set()

    def remove_peer(self, addr: str) -> None:
        """Local membership change: drop a dead voter.  For a running
        cluster, prefer remove_server (replicated)."""
        with self._lock:
            if addr not in self.peers:
                return
            self.peers.remove(addr)
            self._next_index.pop(addr, None)
            self._match_index.pop(addr, None)
        self._wake.set()

    # -- replicated membership changes ---------------------------------

    def _membership(self) -> List[str]:
        """Full voter set (lock held)."""
        return sorted(set(self.peers) | {self.addr})

    def _propose_config(self, mutate, timeout: float):
        """Append a configuration entry; the new member list is derived
        under the lock from the *latest proposed* configuration (the
        most recent config entry in the log, committed or not), so
        concurrent single-server changes chain instead of reverting
        each other — matching hashicorp/raft's rule that the newest
        config entry in the log is the one in effect."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            base = (
                self._proposed_members
                if self._proposed_members is not None
                else self._membership()
            )
            members = mutate(list(base))
            if members is None:
                return None  # no-op against the latest config
            members = sorted(set(members))
            index = self.log.last_index() + 1
            self.log.append(
                LogEntry(
                    index,
                    self.current_term,
                    KIND_CONFIG,
                    pickle.dumps(members),
                )
            )
            self._proposed_members = members
            fut = _Future()
            self._futures[index] = fut
        self._wake.set()
        return fut.wait(timeout)

    def add_server(self, addr: str, timeout: float = 5.0) -> None:
        """Replicated membership change: commit a new voter through the
        log so every replica converges on the same configuration
        (reference: serf join -> raft.AddVoter on the leader)."""

        def mutate(base):
            if addr in base:
                return None
            return base + [addr]

        self._propose_config(mutate, timeout)

    def remove_server(self, addr: str, timeout: float = 5.0) -> None:
        """Replicated membership change: drop a voter through the log
        (reference nomad/autopilot.go dead-server cleanup applies
        raft.RemoveServer, a replicated config change).  Removing the
        leader itself commits the change and then steps down, as
        hashicorp/raft does."""

        def mutate(base):
            if addr not in base:
                return None
            return [m for m in base if m != addr]

        self._propose_config(mutate, timeout)

    def _apply_membership(self, members: List[str]) -> None:
        """Install a committed configuration (lock held)."""
        if self._proposed_members == sorted(members):
            self._proposed_members = None
        if self.addr not in members:
            # we were removed: stop counting ourselves toward quorum
            # and never campaign again (reference: removed servers shut
            # down; a leader steps down on self-removal)
            self.peers = []
            self._next_index.clear()
            self._match_index.clear()
            self._removed = True
            self._deadline = float("inf")
            if self.state == LEADER:
                for fut in self._futures.values():
                    fut.fail(NotLeaderError(None))
                self._futures.clear()
                self._notify_q.put((False, self.current_term))
            self.state = FOLLOWER
            return
        new_peers = [m for m in members if m != self.addr]
        if self.state == LEADER:
            nxt = self.log.last_index() + 1
            for p in new_peers:
                if p not in self.peers:
                    self._next_index[p] = nxt
                    self._match_index[p] = 0
        for p in self.peers:
            if p not in new_peers:
                self._next_index.pop(p, None)
                self._match_index.pop(p, None)
        self.peers = new_peers

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def leader_hint(self) -> Optional[str]:
        with self._lock:
            return self.addr if self.state == LEADER else self.leader_id

    def apply(self, data: bytes, timeout: float = 5.0):
        """Append a command, replicate, and return the FSM's apply result
        once committed (reference nomad/rpc.go:742 raftApply)."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            index = self.log.last_index() + 1
            self.log.append(
                LogEntry(index, self.current_term, KIND_COMMAND, data)
            )
            fut = _Future()
            self._futures[index] = fut
        self._wake.set()  # replicate now
        return fut.wait(timeout)

    def barrier(self, timeout: float = 5.0) -> None:
        """Commit a no-op to confirm leadership / flush the pipeline."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            index = self.log.last_index() + 1
            self.log.append(
                LogEntry(index, self.current_term, KIND_NOOP, b"")
            )
            fut = _Future()
            self._futures[index] = fut
        self._wake.set()
        fut.wait(timeout)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "state": self.state,
                "term": self.current_term,
                "last_log_index": self.log.last_index(),
                "commit_index": self.commit_index,
                "applied_index": self.last_applied,
                "leader": self.leader_hint(),
                "snapshot_index": self.log.snapshot_index,
            }

    # -- driver thread --------------------------------------------------

    def _reset_election_deadline(self) -> None:
        jitter = random.uniform(1.0, 2.0)
        self._deadline = time.monotonic() + self.election_timeout * jitter

    def _driver(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                state = self.state
            if state == LEADER:
                self._replicate_all()
                self._wake.wait(self.heartbeat_interval)
                self._wake.clear()
            else:
                wait = self._deadline - time.monotonic()
                if wait > 0:
                    self._wake.wait(min(wait, 0.02))
                    self._wake.clear()
                    continue
                self._run_election()

    # -- election -------------------------------------------------------

    def _run_election(self) -> None:
        with self._lock:
            if self._removed:
                self._deadline = float("inf")
                return
            self.state = CANDIDATE
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.addr
            self.leader_id = None
            last_index = self.log.last_index()
            last_term = self.log.last_term()
        self._reset_election_deadline()

        votes = 1
        for peer in self.peers:
            try:
                resp = self._rpc(
                    peer,
                    "request_vote",
                    {
                        "term": term,
                        "candidate": self.addr,
                        "last_log_index": last_index,
                        "last_log_term": last_term,
                    },
                )
            except TransportError:
                continue
            if resp["term"] > term:
                self._step_down(resp["term"])
                return
            if resp.get("granted"):
                votes += 1

        with self._lock:
            if self.state != CANDIDATE or self.current_term != term:
                return
            if votes * 2 > len(self.peers) + 1:
                self._become_leader()

    def _become_leader(self) -> None:
        # called with lock held
        self.state = LEADER
        self.leader_id = self.addr
        self._proposed_members = None
        next_idx = self.log.last_index() + 1
        self._next_index = {p: next_idx for p in self.peers}
        self._match_index = {p: 0 for p in self.peers}
        # barrier no-op so entries from prior terms commit promptly
        # (raft §5.4.2; hashicorp/raft LogNoop on leadership)
        self.log.append(
            LogEntry(next_idx, self.current_term, KIND_NOOP, b"")
        )
        self._notify_q.put((True, self.current_term))
        self._wake.set()

    def _step_down(self, term: int) -> None:
        """Become a follower for `term`.  No-op if we have since moved
        to a higher term (so a racing caller can never demote a leader
        legitimately elected at a newer term)."""
        with self._lock:
            if term < self.current_term:
                return
            if term > self.current_term:
                self.current_term = term
                self.voted_for = None
            if self.state == LEADER:
                for fut in self._futures.values():
                    fut.fail(NotLeaderError(self.leader_id))
                self._futures.clear()
                self._notify_q.put((False, self.current_term))
            self.state = FOLLOWER
            self._proposed_members = None
        if not self._removed:
            self._reset_election_deadline()

    # -- replication (leader) ------------------------------------------

    def _replicate_all(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            term = self.current_term
            commit = self.commit_index
        for peer in self.peers:
            self._replicate_one(peer, term, commit)
        self._advance_commit()

    def _replicate_one(self, peer: str, term: int, commit: int) -> None:
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                return
            next_idx = self._next_index.get(peer, 1)
            snap_idx = self.log.snapshot_index
            if next_idx <= snap_idx:
                snapshot = (
                    self._snapshot_data,
                    snap_idx,
                    self.log.snapshot_term,
                    self._snapshot_config,
                )
            else:
                snapshot = None
                prev_index = next_idx - 1
                prev_term = self.log.term_at(prev_index)
                entries = self.log.entries_from(next_idx)

        if snapshot is not None:
            data, s_idx, s_term, s_config = snapshot
            try:
                resp = self._rpc(
                    peer,
                    "install_snapshot",
                    {
                        "term": term,
                        "leader": self.addr,
                        "last_included_index": s_idx,
                        "last_included_term": s_term,
                        "data": data,
                        "config": s_config,
                    },
                )
            except TransportError:
                return
            if resp["term"] > term:
                self._step_down(resp["term"])
                return
            with self._lock:
                self._next_index[peer] = s_idx + 1
                self._match_index[peer] = max(
                    self._match_index.get(peer, 0), s_idx
                )
            return

        if prev_term is None:
            return  # compacted concurrently; next tick sends snapshot
        try:
            resp = self._rpc(
                peer,
                "append_entries",
                {
                    "term": term,
                    "leader": self.addr,
                    "prev_log_index": prev_index,
                    "prev_log_term": prev_term,
                    "entries": [
                        (e.index, e.term, e.kind, e.data) for e in entries
                    ],
                    "leader_commit": commit,
                },
            )
        except TransportError:
            return
        if resp["term"] > term:
            self._step_down(resp["term"])
            return
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                return
            if resp.get("success"):
                if entries:
                    self._match_index[peer] = entries[-1].index
                    self._next_index[peer] = entries[-1].index + 1
            else:
                # back off; use the follower's conflict hint when given
                hint = resp.get("conflict_index")
                self._next_index[peer] = max(
                    1, hint if hint else self._next_index[peer] - 1
                )

    def _advance_commit(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            matches = sorted(
                [self.log.last_index()]
                + [self._match_index.get(p, 0) for p in self.peers]
            )
            # the highest index a strict majority has replicated
            # (ascending order: position n-majority = (n-1)//2)
            majority_idx = matches[(len(matches) - 1) // 2]
            if (
                majority_idx > self.commit_index
                and self.log.term_at(majority_idx) == self.current_term
            ):
                self.commit_index = majority_idx
                self._apply_cv.notify_all()
                # push the advanced commit index to followers NOW
                # (one extra, entry-less append_entries round) instead
                # of letting them sit out a heartbeat interval: every
                # follower-side wait on a committed write — snapshot
                # fences, blocking queries, a fan-out worker catching
                # its local apply up to its own plan — otherwise pays
                # ~heartbeat_interval of pure notification latency.
                # Self-limiting: the wake fires only when the index
                # ADVANCED, and the no-op round it triggers cannot
                # advance it again.
                self._wake.set()

    # -- apply loop -----------------------------------------------------

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                while (
                    self.last_applied >= self.commit_index
                    and not self._stop.is_set()
                ):
                    self._apply_cv.wait(timeout=0.1)
                if self._stop.is_set():
                    return
                index = self.last_applied + 1
                entry = self.log.get(index)
                fut = self._futures.pop(index, None)
            if entry is None:
                # compacted under us (only possible after restore)
                with self._lock:
                    self.last_applied = max(
                        self.last_applied, self.log.snapshot_index
                    )
                continue
            result = None
            error = None
            if entry.kind == KIND_COMMAND:
                try:
                    result = self.fsm.apply(entry.data)
                except Exception as exc:  # noqa: BLE001
                    error = exc
            elif entry.kind == KIND_CONFIG:
                with self._lock:
                    self._apply_membership(pickle.loads(entry.data))
            with self._lock:
                self.last_applied = index
                self._applied_since_snapshot += 1
                should_snap = (
                    self._applied_since_snapshot >= self.snapshot_threshold
                )
            if fut is not None:
                if error is not None:
                    fut.fail(error)
                else:
                    fut.resolve(result)
            if should_snap:
                self._take_snapshot()

    def _take_snapshot(self) -> None:
        """FSM snapshot + log compaction (reference fsm.go Snapshot,
        snapshotsRetained=2 nomad/server.go:64)."""
        data = self.fsm.snapshot()
        with self._lock:
            index = self.last_applied
            term = self.log.term_at(index)
            if term is None:
                return
            self._snapshot_data = data
            # membership as of the applied index, so a catching-up
            # follower restores the config along with the FSM state
            self._snapshot_config = self._membership()
            self.log.compact_through(index, term)
            self._applied_since_snapshot = 0

    # -- RPC handlers ---------------------------------------------------

    def _handle_rpc(self, method: str, payload: dict) -> dict:
        if method == "request_vote":
            return self._on_request_vote(payload)
        if method == "append_entries":
            return self._on_append_entries(payload)
        if method == "install_snapshot":
            return self._on_install_snapshot(payload)
        raise ValueError(f"unknown raft rpc {method!r}")

    def _on_request_vote(self, p: dict) -> dict:
        with self._lock:
            higher = p["term"] > self.current_term
        if higher:
            self._step_down(p["term"])
        with self._lock:
            # re-check under the lock: the term may have moved on while
            # stepping down (a racing local election)
            if p["term"] < self.current_term:
                return {"term": self.current_term, "granted": False}
            up_to_date = (
                p["last_log_term"],
                p["last_log_index"],
            ) >= (self.log.last_term(), self.log.last_index())
            if up_to_date and self.voted_for in (None, p["candidate"]):
                self.voted_for = p["candidate"]
                self._reset_election_deadline()
                return {"term": self.current_term, "granted": True}
            return {"term": self.current_term, "granted": False}

    def _on_append_entries(self, p: dict) -> dict:
        with self._lock:
            if p["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            demote = p["term"] > self.current_term or self.state != FOLLOWER
        if demote:
            self._step_down(p["term"])
        with self._lock:
            # re-check: a racing election may have moved past p's term
            if p["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            self.leader_id = p["leader"]
            self._reset_election_deadline()

            prev_index = p["prev_log_index"]
            prev_term = p["prev_log_term"]
            local_term = self.log.term_at(prev_index)
            if local_term is None or local_term != prev_term:
                # consistency check failed; hint where our log ends
                return {
                    "term": self.current_term,
                    "success": False,
                    "conflict_index": min(
                        self.log.last_index() + 1, prev_index
                    ),
                }
            for index, term, kind, data in p["entries"]:
                existing_term = self.log.term_at(index)
                if existing_term is not None:
                    if existing_term == term:
                        continue
                    self.log.truncate_from(index)
                    # any futures beyond this point died with the old
                    # leader; followers hold none
                if index == self.log.last_index() + 1:
                    self.log.append(LogEntry(index, term, kind, data))
            if p["leader_commit"] > self.commit_index:
                self.commit_index = min(
                    p["leader_commit"], self.log.last_index()
                )
                self._apply_cv.notify_all()
            return {"term": self.current_term, "success": True}

    def _on_install_snapshot(self, p: dict) -> dict:
        with self._lock:
            if p["term"] < self.current_term:
                return {"term": self.current_term}
            demote = p["term"] > self.current_term or self.state != FOLLOWER
        if demote:
            self._step_down(p["term"])
        with self._lock:
            if p["term"] < self.current_term:
                return {"term": self.current_term}
            self.leader_id = p["leader"]
            self._reset_election_deadline()
            idx = p["last_included_index"]
            if idx <= self.log.snapshot_index:
                return {"term": self.current_term}
            self.fsm.restore(p["data"])
            self.log.reset_to_snapshot(idx, p["last_included_term"])
            self._snapshot_data = p["data"]
            if p.get("config"):
                self._apply_membership(p["config"])
                self._snapshot_config = list(p["config"])
            self.commit_index = max(self.commit_index, idx)
            self.last_applied = idx
            self._applied_since_snapshot = 0
            return {"term": self.current_term}
