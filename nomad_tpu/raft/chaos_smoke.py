"""Cluster chaos smoke: a 3-server raft cluster survives repeated
leader kills and a healed partition under continuous eval load.

The control-plane sibling of ``nomad_tpu.parallel.dist_smoke`` and the
device supervisor's fault soaks: deterministic fault injection
(:mod:`nomad_tpu.raft.chaos`) drives the REAL ClusterServer stack —
raft replication, leader-forwarded writes, the batched scheduling hot
path, the leadership fences — through the failure schedule production
hits on real hardware, and asserts the invariants that make failover
"clean":

* **zero lost evals** — every submitted job ends fully placed, every
  eval reaches a terminal status, the broker drains, and the failed
  queue stays empty;
* **zero duplicate placements** — the live placement set (one key per
  job/task-group/alloc-name) equals a fault-free oracle run's set
  exactly: no double-committed wave ever produced a second live alloc;
* **monotone FSM apply indices** — no server ever applies backwards;
* **bounded failover** — every kill's revoke→re-establish
  detect-to-resume time is recorded (the ``cluster_failover`` bench
  block).

Usage::

    python -m nomad_tpu.raft.chaos_smoke [--jobs N] [--kills K]
        [--nodes M] [--seed S] [--json PATH]

``NOMAD_TPU_CLUSTER_FAULT=msg_drop:5`` (or ``slow_wire:2``) layers
wire-level faults over the kill/heal schedule; ``leader_kill`` and
``partition`` specs are the schedule the smoke already runs.
Exit code 0 = every invariant held; 2 = a violation (the JSON names
it).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..raft.chaos import ChaosTransport, armed_fault
from ..raft.transport import TransportError
from ..raft import NotLeaderError

HEARTBEAT_TTL = 300.0  # no TTL expiries during the smoke


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    ordered = sorted(vals)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


def _job_specs(n: int) -> List[Tuple[str, int]]:
    """(job id, alloc count) — small single-alloc jobs so the load is
    eval-count-bound, not capacity-bound."""
    return [(f"chaos-{i:05d}", 1) for i in range(n)]


def _make_job(job_id: str, count: int):
    from .. import mock

    job = mock.job(id=job_id)
    job.task_groups[0].count = count
    # tiny asks: the smoke is eval-count-bound by design — capacity
    # must never block an eval, or "zero lost" would be unprovable
    for tg in job.task_groups:
        for task in tg.tasks:
            task.resources.cpu = 50
            task.resources.memory_mb = 32
    return job


def _established_leader(servers, timeout: float = 15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        est = [
            s
            for s in servers
            if s.is_leader() and s._leader_established
        ]
        if est:
            return est[0]
        time.sleep(0.01)
    raise AssertionError("no established leader")


def _live_placements(store) -> Set[Tuple[str, str, str]]:
    """One key per live alloc: (job id, task group, alloc name).
    Alloc ids are random, so oracle comparison keys on the
    deterministic name — a duplicate placement shows up as either a
    key collision (caught below) or an extra live alloc count."""
    out: Set[Tuple[str, str, str]] = set()
    for alloc in store.allocs.values():
        if alloc.terminal_status():
            continue
        out.add((alloc.job_id, alloc.task_group, alloc.name))
    return out


def _run_cluster(
    specs: List[Tuple[str, int]],
    nodes: int,
    seed: int,
    kills: int = 0,
    partition_cycle: bool = False,
    submit_pause_s: float = 0.0,
) -> Dict:
    """Boot a 3-server cluster on a ChaosTransport, push the job load
    through it while the fault schedule runs, settle, and return the
    final state + failover timings.  ``kills=0`` is the fault-free
    oracle configuration (same topology, same transport class, no
    faults armed — only the schedule differs).  With
    ``NOMAD_TPU_FANOUT=1`` in the environment (the ``--fanout``
    flag), followers plan through the whole schedule — every kill
    then also exercises remote leases dying with the leadership and
    follower plans being fenced by the replicated generation check."""
    from ..server.cluster import TestCluster

    transport = ChaosTransport(seed=seed)
    cluster = TestCluster(
        3, transport=transport, heartbeat_ttl=HEARTBEAT_TTL
    )
    monotone_ok = True
    violation = [""]
    stop_sampler = threading.Event()

    def sample_indices() -> None:
        nonlocal monotone_ok
        last: Dict[str, int] = {}
        while not stop_sampler.is_set():
            for s in cluster.servers:
                applied = s.raft.stats()["applied_index"]
                if applied < last.get(s.addr, 0):
                    monotone_ok = False
                    violation[0] = (
                        f"{s.addr} applied index went backwards: "
                        f"{last[s.addr]} -> {applied}"
                    )
                last[s.addr] = applied
            time.sleep(0.02)

    t_start = time.monotonic()
    detect_to_resume: List[float] = []
    submitted: List[str] = []
    submit_errors = [0]
    try:
        cluster.start()
        leader = _established_leader(cluster.servers)
        if kills:
            # wire-level faults (msg_drop/slow_wire) layer over the
            # kill schedule when armed via NOMAD_TPU_CLUSTER_FAULT
            transport.arm(armed_fault())
        sampler = threading.Thread(
            target=sample_indices, name="chaos-sampler", daemon=True
        )
        sampler.start()

        from .. import mock

        for _ in range(nodes):
            leader.register_node(mock.node())

        def submit_all() -> None:
            """At-least-once submission with retry across servers —
            the client side of a leader failover.  Job registration
            is idempotent on the job id, so a retry after an
            ambiguous failure cannot double-place."""
            rr = 0
            for job_id, count in specs:
                if submit_pause_s:
                    time.sleep(submit_pause_s)
                for attempt in range(200):
                    server = cluster.servers[rr % len(cluster.servers)]
                    rr += 1
                    try:
                        server.register_job(_make_job(job_id, count))
                        submitted.append(job_id)
                        break
                    except (
                        NotLeaderError,
                        TransportError,
                        TimeoutError,
                        RuntimeError,
                        KeyError,
                    ):
                        submit_errors[0] += 1
                        time.sleep(0.02)
                else:
                    raise AssertionError(
                        f"could not submit {job_id} after 200 tries"
                    )

        submitter = threading.Thread(
            target=submit_all, name="chaos-submitter", daemon=True
        )
        submitter.start()

        for kill in range(kills):
            # let load flow before each kill so leases/chains are
            # genuinely in flight when leadership dies
            time.sleep(0.4)
            victim = _established_leader(cluster.servers)
            t0 = time.monotonic()
            transport.partition_group([victim.addr])
            others = [s for s in cluster.servers if s is not victim]
            # generous: a re-elected server's establish can queue
            # behind its own previous revoke drain (ordered
            # leadership notifications), which in the worst case
            # waits out a full quorumless forward-retry cycle
            new_leader = _established_leader(others, timeout=60.0)
            detect_to_resume.append(time.monotonic() - t0)
            transport.heal(victim.addr)
            # the deposed leader steps down (and revokes) on first
            # contact with the new term
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and (
                victim.is_leader() or victim._leader_established
            ):
                time.sleep(0.01)

        if partition_cycle:
            # partition a FOLLOWER away under load, then heal: it must
            # catch up (log replay or snapshot install) and converge
            time.sleep(0.2)
            current = _established_leader(cluster.servers)
            follower = next(
                s for s in cluster.servers if s is not current
            )
            transport.partition_group([follower.addr])
            time.sleep(1.0)
            transport.heal(follower.addr)

        submitter.join(timeout=240.0)
        if submitter.is_alive():
            raise AssertionError("submitter wedged")

        # settle: a final leader must drain every eval to a terminal
        # status and place every job (restore_evals on each establish
        # re-enqueues whatever a revoke unacked)
        deadline = time.monotonic() + 120.0
        leader = None
        while time.monotonic() < deadline:
            leader = _established_leader(cluster.servers)
            store = leader.store
            pending = [
                ev
                for ev in list(store.evals.values())
                if ev.status in ("pending", "blocked")
            ]
            placed = sum(
                1
                for job_id, count in specs
                if len(
                    [
                        a
                        for a in store.allocs_by_job(
                            "default", job_id
                        )
                        if not a.terminal_status()
                    ]
                )
                == count
            )
            if (
                not pending
                and placed == len(specs)
                and leader.drain_to_idle(timeout=1.0)
            ):
                break
            time.sleep(0.1)

        store = leader.store
        placements = _live_placements(store)
        live_by_key: Dict[Tuple[str, str, str], int] = {}
        for alloc in store.allocs.values():
            if alloc.terminal_status():
                continue
            key = (alloc.job_id, alloc.task_group, alloc.name)
            live_by_key[key] = live_by_key.get(key, 0) + 1
        duplicates = {
            k: n for k, n in live_by_key.items() if n > 1
        }
        lost = [
            job_id
            for job_id, count in specs
            if len(
                [
                    a
                    for a in store.allocs_by_job("default", job_id)
                    if not a.terminal_status()
                ]
            )
            != count
        ]
        nonterminal = [
            ev.id
            for ev in list(store.evals.values())
            if ev.status in ("pending", "blocked")
        ]
        failed_q = len(leader.broker.failed())
        counters = {
            name: sum(
                s.metrics.get_counter(name) for s in cluster.servers
            )
            for name in (
                "leadership.establishes",
                "leadership.revokes",
                "leadership.unacked_on_revoke",
                "leadership.chain_aborts",
                "leadership.plan_rejected",
                "leadership.stale_wave_fenced",
                "raft.forward_retries",
                # follower fan-out (0 unless NOMAD_TPU_FANOUT=1):
                # plans actually produced on followers, and submits
                # a leadership move rejected mid-flight
                "fanout.plans_submitted",
                "fanout.plan_not_leader",
                "fanout.lease_gen_flips",
            )
        }
        return {
            "placements": placements,
            "duplicates": duplicates,
            "lost_jobs": lost,
            "nonterminal_evals": len(nonterminal),
            "failed_queue": failed_q,
            "evals_total": len(store.evals),
            "submitted": len(submitted),
            "submit_errors": submit_errors[0],
            "detect_to_resume_s": detect_to_resume,
            "monotone_ok": monotone_ok,
            "monotone_violation": violation[0],
            "counters": counters,
            "dropped_rpcs": transport.dropped,
            "elapsed_s": time.monotonic() - t_start,
        }
    finally:
        stop_sampler.set()
        transport.disarm()
        cluster.stop()


def run_smoke(
    jobs: int = 400,
    kills: int = 5,
    nodes: int = 6,
    seed: int = 0,
    fanout: bool = False,
) -> Dict:
    """Oracle run + chaos run + invariant checks; returns the
    ``cluster_failover`` block (``ok`` tells whether every invariant
    held).  ``fanout=True`` arms ``NOMAD_TPU_FANOUT=1`` for BOTH
    runs: followers plan throughout, so the kill schedule also
    exercises remote-lease reclamation and the replicated generation
    fence on follower plans — and the smoke additionally asserts the
    fan-out actually engaged (follower plans > 0)."""
    import os as _os

    specs = _job_specs(jobs)
    saved = _os.environ.get("NOMAD_TPU_FANOUT")
    if fanout:
        _os.environ["NOMAD_TPU_FANOUT"] = "1"
    try:
        oracle = _run_cluster(
            specs, nodes=nodes, seed=seed, kills=0
        )
        chaos = _run_cluster(
            specs,
            nodes=nodes,
            seed=seed,
            kills=kills,
            partition_cycle=True,
        )
    finally:
        if fanout:
            if saved is None:
                _os.environ.pop("NOMAD_TPU_FANOUT", None)
            else:
                _os.environ["NOMAD_TPU_FANOUT"] = saved
    oracle_match = chaos["placements"] == oracle["placements"]
    fanout_engaged = (
        not fanout
        or chaos["counters"]["fanout.plans_submitted"] > 0
    )
    ok = (
        oracle_match
        and not chaos["duplicates"]
        and not chaos["lost_jobs"]
        and chaos["nonterminal_evals"] == 0
        and chaos["failed_queue"] == 0
        and chaos["monotone_ok"]
        and oracle["monotone_ok"]
        and len(chaos["detect_to_resume_s"]) == kills
        and fanout_engaged
    )
    dtr = chaos["detect_to_resume_s"]
    return {
        "ok": ok,
        "servers": 3,
        "fanout": fanout,
        "fanout_engaged": fanout_engaged,
        "jobs": jobs,
        "nodes": nodes,
        "seed": seed,
        "kills": kills,
        "partition_cycles": 1,
        "evals_total": chaos["evals_total"],
        "placements_total": len(chaos["placements"]),
        "oracle_placements_total": len(oracle["placements"]),
        "oracle_match": oracle_match,
        "lost_evals": len(chaos["lost_jobs"])
        + chaos["nonterminal_evals"],
        "lost_jobs": chaos["lost_jobs"][:10],
        "duplicate_placements": len(chaos["duplicates"]),
        "failed_queue": chaos["failed_queue"],
        "apply_monotone": chaos["monotone_ok"]
        and oracle["monotone_ok"],
        "monotone_violation": chaos["monotone_violation"]
        or oracle["monotone_violation"],
        "detect_to_resume_s": [round(v, 4) for v in dtr],
        "detect_to_resume_p50_s": round(_percentile(dtr, 0.5), 4),
        "detect_to_resume_max_s": round(max(dtr), 4) if dtr else 0.0,
        "submit_errors": chaos["submit_errors"],
        "dropped_rpcs": chaos["dropped_rpcs"],
        "counters": chaos["counters"],
        "oracle_elapsed_s": round(oracle["elapsed_s"], 2),
        "chaos_elapsed_s": round(chaos["elapsed_s"], 2),
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="3-server leadership-loss chaos smoke"
    )
    parser.add_argument("--jobs", type=int, default=400)
    parser.add_argument("--kills", type=int, default=5)
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--fanout",
        action="store_true",
        help="run with follower scheduling fan-out enabled "
        "(NOMAD_TPU_FANOUT=1 for both the oracle and chaos runs)",
    )
    parser.add_argument(
        "--json", default="", help="also write the block to this path"
    )
    args = parser.parse_args(argv)
    block = run_smoke(
        jobs=args.jobs,
        kills=args.kills,
        nodes=args.nodes,
        seed=args.seed,
        fanout=args.fanout,
    )
    out = {"cluster_failover": block}
    print(json.dumps(out, indent=2, default=str))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(out, fh, indent=2, default=str)
    if not block["ok"]:
        print("CHAOS_SMOKE: FAIL", file=sys.stderr)
        return 2
    print(
        "CHAOS_SMOKE: ok — %d kills survived, %d placements, "
        "0 lost, 0 duplicates"
        % (block["kills"], block["placements_total"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
