"""Raft RPC transports.

InmemTransport mirrors hashicorp/raft's InmemTransport (what
nomad.TestServer clusters use, nomad/testing.go:44): a process-local
registry of nodes, synchronous delivery, and partition controls for
failure-injection tests.  The same handler surface is served over
framed TCP by TcpTransport (nomad_tpu/raft/tcp.py) for cross-process
clusters — the reference's RaftLayer likewise multiplexes raft traffic
over the server's single RPC port (nomad/raft_rpc.go).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple


class TransportError(Exception):
    """Delivery failure (peer down, partitioned, or timeout)."""


Handler = Callable[[str, dict], dict]


class InmemTransport:
    """Shared in-process message bus.  One instance per test cluster;
    every node registers its RPC handler under its address."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._handlers: Dict[str, Handler] = {}
        self._down: set = set()
        self._partitions: set = set()  # frozenset({a, b}) pairs

    def register(self, addr: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[addr] = handler

    def deregister(self, addr: str) -> None:
        with self._lock:
            self._handlers.pop(addr, None)

    # -- failure injection ---------------------------------------------

    def set_down(self, addr: str, down: bool = True) -> None:
        with self._lock:
            if down:
                self._down.add(addr)
            else:
                self._down.discard(addr)

    def partition(self, a: str, b: str) -> None:
        with self._lock:
            self._partitions.add(frozenset((a, b)))

    def heal(self, a: Optional[str] = None, b: Optional[str] = None) -> None:
        """heal() clears everything; heal(a) removes every partition
        involving a; heal(a, b) removes just that pair."""
        with self._lock:
            if a is None:
                self._partitions.clear()
                self._down.clear()
            elif b is None:
                self._partitions = {
                    p for p in self._partitions if a not in p
                }
                self._down.discard(a)
            else:
                self._partitions.discard(frozenset((a, b)))

    def isolate(self, addr: str) -> None:
        """Partition addr from every other registered node."""
        with self._lock:
            for other in self._handlers:
                if other != addr:
                    self._partitions.add(frozenset((addr, other)))

    # -- delivery -------------------------------------------------------

    def _check(self, src: str, dst: str) -> Handler:
        with self._lock:
            if dst in self._down or src in self._down:
                raise TransportError(f"{dst} unreachable")
            if frozenset((src, dst)) in self._partitions:
                raise TransportError(f"{src} partitioned from {dst}")
            handler = self._handlers.get(dst)
        if handler is None:
            raise TransportError(f"no handler for {dst}")
        return handler

    def rpc(self, src: str, dst: str, method: str, payload: dict) -> dict:
        """Deliver one RPC.  TransportError covers delivery failures
        only; application exceptions from the remote handler propagate
        with their real type (in-process calls — the reference's
        net/rpc likewise round-trips typed server errors)."""
        handler = self._check(src, dst)
        return handler(method, payload)
