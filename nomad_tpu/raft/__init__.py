"""Raft consensus for the replicated control plane.

The reference replicates its state machine with hashicorp/raft
(nomad/server.go:105-109, nomad/raft_rpc.go) and applies committed log
entries through the FSM dispatch (nomad/fsm.go:180).  This package is a
self-contained Raft implementation with the same shape: a replicated
log, leader election with randomized timeouts, AppendEntries/RequestVote
/InstallSnapshot RPCs over a pluggable transport, log compaction via FSM
snapshots, and a leadership-observer channel that drives the
establish/revoke-leadership lifecycle (nomad/leader.go:54
monitorLeadership).
"""
from .log import LogEntry, RaftLog
from .node import RaftNode, NotLeaderError
from .tcp import TcpTransport
from .transport import InmemTransport, TransportError

__all__ = [
    "LogEntry",
    "RaftLog",
    "RaftNode",
    "NotLeaderError",
    "InmemTransport",
    "TcpTransport",
    "TransportError",
]
