"""nomad-tpu: a TPU-native workload-orchestration framework.

A brand-new framework with the capabilities of HashiCorp Nomad (reference:
/root/reference, v0.13.0-dev), re-designed TPU-first rather than ported.

The defining feature is the scheduling backend: the reference's per-node
ranking loop (`scheduler/stack.go:116 GenericStack.Select` -> feasibility
checks -> BinPack/Spread/NodeAffinity iterators -> `nomad/structs/funcs.go:175
ScoreFitBinPack`) becomes a single vectorized score matrix over
(candidate-nodes x placements) computed under `jax.jit`, with feasibility as
boolean masks, deterministic emulation of the reference's limited-walk
selection, and top-k/argmax placement picks.  The node axis shards over a
`jax.sharding.Mesh` for multi-chip scale.

Layout (mirrors SURVEY.md section 7):
  structs/   -- data model: Job/TaskGroup/Task/Node/Allocation/Eval/Plan,
                resource math, network index
  state/     -- in-memory MVCC state store + columnar NodeTable (the
                TPU-resident "cluster tensor")
  sched/     -- schedulers: reference-semantics oracle chain, the TPU stack,
                reconciler, generic/batch/system schedulers, harness
  ops/       -- JAX kernels: score matrix, constraint LUT compilation,
                selection emulation
  parallel/  -- device mesh + shardings (node axis / eval-batch axis)
  server/    -- control plane: eval broker, blocked evals, plan queue,
                plan applier, workers
"""

__version__ = "0.1.0"
