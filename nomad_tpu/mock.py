"""Canonical test fixtures (reference nomad/mock/mock.go)."""
from __future__ import annotations

import itertools
from typing import Optional

from .structs import (
    Affinity,
    Allocation,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Constraint,
    Evaluation,
    EVAL_TRIGGER_JOB_REGISTER,
    Job,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    Node,
    NODE_STATUS_READY,
    NodeDeviceResource,
    NodeResources,
    NodeReservedResources,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    alloc_name,
    compute_node_class,
    new_id,
)

_counter = itertools.count()


def node(**overrides) -> Node:
    """(reference mock.go:13 Node)"""
    i = next(_counter)
    n = Node(
        name=f"node-{i}",
        datacenter="dc1",
        node_class="",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "0.13.0",
            "driver.exec": "1",
            "driver.mock_driver": "1",
            "cpu.frequency": "2600",
            "cpu.numcores": "4",
        },
        node_resources=NodeResources(
            cpu=4000, memory_mb=8192, disk_mb=100 * 1024
        ),
        reserved_resources=NodeReservedResources(
            cpu=100, memory_mb=256, disk_mb=4 * 1024
        ),
        drivers={"exec": True, "mock_driver": True},
        status=NODE_STATUS_READY,
    )
    for key, value in overrides.items():
        setattr(n, key, value)
    n.computed_class = compute_node_class(n)
    return n


def csi_volume(plugin_id: str = "ebs0", **overrides):
    """(reference mock.go CSIVolume)"""
    from .structs import CSIVolume

    i = next(_counter)
    v = CSIVolume(
        id=f"vol-{i}",
        name=f"vol-{i}",
        plugin_id=plugin_id,
    )
    for key, value in overrides.items():
        setattr(v, key, value)
    return v


def nvidia_node(**overrides) -> Node:
    """(reference mock.go:114 NvidiaNode)"""
    n = node(**overrides)
    n.node_resources.devices = [
        NodeDeviceResource(
            vendor="nvidia",
            type="gpu",
            name="1080ti",
            instance_ids=[new_id() for _ in range(4)],
            attributes={
                "memory": "11169",
                "cuda_cores": "3584",
                "graphics_clock": "1480",
                "memory_bandwidth": "11",
            },
        )
    ]
    n.computed_class = compute_node_class(n)
    return n


def job(**overrides) -> Job:
    """(reference mock.go:175 Job)"""
    job_id = overrides.pop("id", new_id())
    j = Job(
        id=job_id,
        name="my-job",
        type=JOB_TYPE_SERVICE,
        priority=50,
        datacenters=["dc1"],
        constraints=[
            Constraint(
                ltarget="${attr.kernel.name}", rtarget="linux", operand="="
            )
        ],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                restart_policy=RestartPolicy(
                    attempts=3, interval_s=600, delay_s=60, mode="delay"
                ),
                reschedule_policy=ReschedulePolicy(
                    attempts=2,
                    interval_s=600,
                    delay_s=5,
                    delay_function="constant",
                    max_delay_s=3600,
                    unlimited=False,
                ),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        env={"FOO": "bar"},
                        resources=Resources(cpu=500, memory_mb=256),
                    )
                ],
            )
        ],
        status="pending",
    )
    for key, value in overrides.items():
        setattr(j, key, value)
    return j


def batch_job(**overrides) -> Job:
    """(reference mock.go BatchJob)"""
    j = job(**overrides)
    j.type = JOB_TYPE_BATCH
    for tg in j.task_groups:
        tg.reschedule_policy = ReschedulePolicy(
            attempts=1,
            interval_s=24 * 3600,
            delay_s=5,
            delay_function="constant",
            unlimited=False,
        )
    return j


def system_job(**overrides) -> Job:
    """(reference mock.go:790 SystemJob)"""
    j = job(**overrides)
    j.type = JOB_TYPE_SYSTEM
    j.task_groups[0].count = 1
    for tg in j.task_groups:
        tg.reschedule_policy = None
    return j


def evaluation(**overrides) -> Evaluation:
    """(reference mock.go:865 Eval)"""
    e = Evaluation(
        priority=50,
        type=JOB_TYPE_SERVICE,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
    )
    for key, value in overrides.items():
        setattr(e, key, value)
    return e


def alloc(**overrides) -> Allocation:
    """(reference mock.go:894 Alloc)"""
    j = overrides.pop("job", None) or job()
    tg = j.task_groups[0]
    a = Allocation(
        namespace=j.namespace,
        eval_id=new_id(),
        node_id="12345678-abcd-efab-cdef-123456789abc",
        job_id=j.id,
        job=j,
        task_group=tg.name,
        name=alloc_name(j.id, tg.name, 0),
        allocated_resources=AllocatedResources(
            tasks={
                tg.tasks[0].name: AllocatedTaskResources(
                    cpu=500, memory_mb=256
                )
            },
            shared=AllocatedSharedResources(disk_mb=150),
        ),
        desired_status="run",
        client_status="pending",
    )
    for key, value in overrides.items():
        setattr(a, key, value)
    return a
