"""Jobspec parser (reference jobspec/parse.go:26 Parse).

Parses the HCL-style job file dialect into a `structs.Job`:

    job "example" {
      datacenters = ["dc1"]
      type        = "service"
      group "web" {
        count = 3
        constraint { attribute = "${attr.kernel.name}" value = "linux" }
        update { max_parallel = 2 canary = 1 }
        task "server" {
          driver = "exec"
          config { command = "/bin/sleep" args = ["600"] }
          resources { cpu = 500 memory = 256 }
          env { FOO = "bar" }
        }
      }
    }

A hand-rolled tokenizer + recursive-descent block parser covering the
HCL1 subset job files actually use: string/number/bool scalars, lists,
`key = value` assignments, labeled and unlabeled blocks, comments (#,
//, /* */).  JSON job payloads bypass this via api/codec.job_from_dict.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple, Union

from .api.codec import job_from_dict
from .structs import Job

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<punct>[{}\[\],=])
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
    """,
    re.VERBOSE | re.DOTALL,
)


class ParseError(ValueError):
    pass


def _tokenize(text: str) -> List[Tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise ParseError(f"unexpected character {text[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        tokens.append((kind, m.group()))
    return tokens


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]) -> None:
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise ParseError("unexpected end of input")
        self.pos += 1
        return tok

    def expect(self, value: str) -> None:
        kind, tok = self.next()
        if tok != value:
            raise ParseError(f"expected {value!r}, got {tok!r}")

    # -- grammar --------------------------------------------------------

    def parse_body(self, stop: Optional[str] = "}") -> Dict[str, Any]:
        """A body is a sequence of assignments and blocks.  Repeated
        blocks accumulate into lists under the block name."""
        out: Dict[str, Any] = {}
        while True:
            tok = self.peek()
            if tok is None:
                if stop is None:
                    return out
                raise ParseError(f"expected {stop!r}, got end of input")
            if tok[1] == stop:
                self.next()
                return out
            self._parse_item(out)

    def _parse_item(self, out: Dict[str, Any]) -> None:
        kind, name = self.next()
        if kind == "string":
            name = _unquote(name)
        elif kind != "ident":
            raise ParseError(f"expected identifier, got {name!r}")

        tok = self.peek()
        if tok is None:
            raise ParseError("unexpected end after " + name)

        if tok[1] == "=":
            self.next()
            out[name] = self._parse_value()
            return

        # block: optional labels then {
        labels: List[str] = []
        while tok is not None and tok[0] == "string":
            labels.append(_unquote(self.next()[1]))
            tok = self.peek()
        if tok is None or tok[1] != "{":
            raise ParseError(
                f"expected '{{' after block {name!r}, got "
                f"{tok[1] if tok else 'EOF'!r}"
            )
        self.next()
        body = self.parse_body("}")
        if labels:
            body["__label__"] = labels[0]
        existing = out.get(name)
        if existing is None:
            out[name] = [body]
        elif isinstance(existing, list):
            existing.append(body)
        else:
            out[name] = [existing, body]

    def _parse_value(self) -> Any:
        kind, tok = self.next()
        if kind == "string":
            return _unquote(tok)
        if kind == "number":
            return float(tok) if "." in tok else int(tok)
        if kind == "ident":
            if tok == "true":
                return True
            if tok == "false":
                return False
            return tok
        if tok == "[":
            items = []
            while True:
                nxt = self.peek()
                if nxt is None:
                    raise ParseError("unterminated list")
                if nxt[1] == "]":
                    self.next()
                    return items
                items.append(self._parse_value())
                if self.peek() and self.peek()[1] == ",":
                    self.next()
        if tok == "{":
            return self.parse_body("}")
        raise ParseError(f"unexpected token {tok!r}")


def _unquote(raw: str) -> str:
    body = raw[1:-1]
    return body.replace('\\"', '"').replace("\\\\", "\\").replace(
        "\\n", "\n"
    ).replace("\\t", "\t")


def _first(blocks, default=None):
    if isinstance(blocks, list):
        return blocks[0] if blocks else default
    return blocks if blocks is not None else default


def _all(blocks) -> List[Dict]:
    if blocks is None:
        return []
    if isinstance(blocks, list):
        return blocks
    return [blocks]


# ---------------------------------------------------------------------------
# HCL tree -> API dict -> Job
# ---------------------------------------------------------------------------


def _constraint_dicts(body: Dict) -> List[Dict]:
    out = []
    for c in _all(body.get("constraint")):
        operand = c.get("operator", c.get("operand", "="))
        ltarget = c.get("attribute", "")
        rtarget = str(c.get("value", ""))
        # sugar forms (reference jobspec/parse.go parseConstraints)
        for sugar in (
            "version",
            "semver",
            "regexp",
            "distinct_hosts",
            "distinct_property",
            "set_contains",
        ):
            if sugar in c:
                operand = sugar
                if sugar in ("distinct_hosts",):
                    rtarget = ""
                elif sugar == "distinct_property":
                    ltarget = str(c[sugar])
                    rtarget = str(c.get("value", ""))
                else:
                    rtarget = str(c[sugar])
        out.append(
            {"ltarget": ltarget, "rtarget": rtarget, "operand": operand}
        )
    return out


def _affinity_dicts(body: Dict) -> List[Dict]:
    out = []
    for a in _all(body.get("affinity")):
        operand = a.get("operator", "=")
        rtarget = str(a.get("value", ""))
        for sugar in ("version", "semver", "regexp", "set_contains"):
            if sugar in a:
                operand = sugar
                rtarget = str(a[sugar])
        out.append(
            {
                "ltarget": a.get("attribute", ""),
                "rtarget": rtarget,
                "operand": operand,
                "weight": int(a.get("weight", 50)),
            }
        )
    return out


def _spread_dicts(body: Dict) -> List[Dict]:
    out = []
    for s in _all(body.get("spread")):
        targets = [
            {
                "value": t.get("__label__", t.get("value", "")),
                "percent": int(t.get("percent", 0)),
            }
            for t in _all(s.get("target"))
        ]
        out.append(
            {
                "attribute": s.get("attribute", ""),
                "weight": int(s.get("weight", 50)),
                "targets": targets,
            }
        )
    return out


def _network_dicts(body: Dict) -> List[Dict]:
    out = []
    for n in _all(body.get("network")):
        reserved, dynamic = [], []
        for p in _all(n.get("port")):
            label = p.get("__label__", "")
            if "static" in p:
                reserved.append(
                    {"label": label, "value": int(p["static"]),
                     "to": int(p.get("to", 0))}
                )
            else:
                dynamic.append(
                    {"label": label, "to": int(p.get("to", 0))}
                )
        out.append(
            {
                "mode": n.get("mode", "host"),
                "mbits": int(n.get("mbits", 0)),
                "reserved_ports": reserved,
                "dynamic_ports": dynamic,
            }
        )
    return out


def _duration_s(value, default: float) -> float:
    """Parse 30, "30s", "5m", "1h30m" — delegates to the canonical
    parser in config.py (single implementation; the old copy here had
    the 'ms'-after-'m' alternation bug)."""
    from .config import _duration_s as _parse

    return _parse(value, default)


def _task_dict(body: Dict) -> Dict:
    resources = _first(body.get("resources"), {}) or {}
    devices = [
        {
            "name": d.get("__label__", d.get("name", "")),
            "count": int(d.get("count", 1)),
            "constraints": _constraint_dicts(d),
            "affinities": _affinity_dicts(d),
        }
        for d in _all(resources.get("device"))
    ]
    return {
        "name": body.get("__label__", body.get("name", "")),
        "driver": body.get("driver", "exec"),
        "config": _first(body.get("config"), {}) or {},
        "env": _first(body.get("env"), {}) or {},
        "resources": {
            "cpu": int(resources.get("cpu", 100)),
            "memory_mb": int(
                resources.get("memory", resources.get("memory_mb", 300))
            ),
            "networks": _network_dicts(resources),
            "devices": devices,
        },
        "constraints": _constraint_dicts(body),
        "affinities": _affinity_dicts(body),
        "services": [_service_dict(s) for s in _all(body.get("service"))],
        "leader": bool(body.get("leader", False)),
        "kill_timeout_s": _duration_s(body.get("kill_timeout"), 5.0),
        "meta": _first(body.get("meta"), {}) or {},
    }


def _service_dict(body: Dict) -> Dict:
    """service stanza incl. connect (reference jobspec/parse_service.go
    + parse for connect/sidecar_service/proxy/upstreams)."""
    out = {
        "name": body.get("__label__", body.get("name", "")),
        "port_label": str(body.get("port", "")),
        "tags": body.get("tags", []) or [],
        "checks": [
            {
                "type": c.get("type", "tcp"),
                "name": c.get("__label__", c.get("name", "")),
                "path": c.get("path", ""),
                "interval_s": _duration_s(c.get("interval"), 10.0),
                "timeout_s": _duration_s(c.get("timeout"), 2.0),
            }
            for c in _all(body.get("check"))
        ],
    }
    connect = _first(body.get("connect"))
    if connect:
        sidecar = _first(connect.get("sidecar_service"))
        proxy = _first(sidecar.get("proxy")) if sidecar else None
        out["connect"] = {
            "native": bool(connect.get("native", False)),
            "sidecar_service": sidecar is not None,
            "upstreams": [
                {
                    "destination_name": u.get("destination_name", ""),
                    "local_bind_port": int(
                        u.get("local_bind_port", 0)
                    ),
                }
                for u in _all((proxy or {}).get("upstreams"))
            ],
        }
    return out


def _update_dict(body: Dict) -> Dict:
    return {
        "stagger_s": _duration_s(body.get("stagger"), 30.0),
        "max_parallel": int(body.get("max_parallel", 1)),
        "min_healthy_time_s": _duration_s(
            body.get("min_healthy_time"), 10.0
        ),
        "healthy_deadline_s": _duration_s(
            body.get("healthy_deadline"), 300.0
        ),
        "progress_deadline_s": _duration_s(
            body.get("progress_deadline"), 600.0
        ),
        "auto_revert": bool(body.get("auto_revert", False)),
        "auto_promote": bool(body.get("auto_promote", False)),
        "canary": int(body.get("canary", 0)),
    }


def _group_dict(body: Dict) -> Dict:
    out = {
        "name": body.get("__label__", body.get("name", "")),
        "count": int(body.get("count", 1)),
        "tasks": [_task_dict(t) for t in _all(body.get("task"))],
        "constraints": _constraint_dicts(body),
        "affinities": _affinity_dicts(body),
        "spreads": _spread_dicts(body),
        "networks": _network_dicts(body),
        "meta": _first(body.get("meta"), {}) or {},
    }
    rp = _first(body.get("restart"))
    if rp:
        out["restart_policy"] = {
            "attempts": int(rp.get("attempts", 2)),
            "interval_s": _duration_s(rp.get("interval"), 1800.0),
            "delay_s": _duration_s(rp.get("delay"), 15.0),
            "mode": rp.get("mode", "fail"),
        }
    rsp = _first(body.get("reschedule"))
    if rsp:
        out["reschedule_policy"] = {
            "attempts": int(rsp.get("attempts", 0)),
            "interval_s": _duration_s(rsp.get("interval"), 0.0),
            "delay_s": _duration_s(rsp.get("delay"), 30.0),
            "delay_function": rsp.get("delay_function", "exponential"),
            "max_delay_s": _duration_s(rsp.get("max_delay"), 3600.0),
            "unlimited": bool(rsp.get("unlimited", True)),
        }
    upd = _first(body.get("update"))
    if upd:
        out["update"] = _update_dict(upd)
    mig = _first(body.get("migrate"))
    if mig:
        out["migrate"] = {
            "max_parallel": int(mig.get("max_parallel", 1))
        }
    disk = _first(body.get("ephemeral_disk"))
    if disk:
        out["ephemeral_disk"] = {
            "sticky": bool(disk.get("sticky", False)),
            "size_mb": int(disk.get("size", disk.get("size_mb", 300))),
            "migrate": bool(disk.get("migrate", False)),
        }
    vols = {}
    for v in _all(body.get("volume")):
        name = v.get("__label__", "")
        vols[name] = {
            "type": v.get("type", "host"),
            "source": v.get("source", ""),
            "read_only": bool(v.get("read_only", False)),
        }
    if vols:
        out["volumes"] = vols
    sc = _first(body.get("scaling"))
    if sc:
        out["scaling"] = {
            "min": int(sc.get("min", 1)),
            "max": int(sc.get("max", 0)),
            "enabled": bool(sc.get("enabled", True)),
            "policy": _first(sc.get("policy"), {}) or {},
        }
    return out


def parse(text: str) -> Job:
    """Parse an HCL job file into a Job."""
    tree = _Parser(_tokenize(text)).parse_body(stop=None)
    jobs = _all(tree.get("job"))
    if not jobs:
        raise ParseError("no 'job' block found")
    body = jobs[0]
    job_dict = {
        "id": body.get("__label__", body.get("id", "")),
        "name": body.get("name", body.get("__label__", "")),
        "namespace": body.get("namespace", "default"),
        "region": body.get("region", "global"),
        "type": body.get("type", "service"),
        "priority": int(body.get("priority", 50)),
        "datacenters": body.get("datacenters", ["dc1"]),
        "all_at_once": bool(body.get("all_at_once", False)),
        "task_groups": [_group_dict(g) for g in _all(body.get("group"))],
        "constraints": _constraint_dicts(body),
        "affinities": _affinity_dicts(body),
        "spreads": _spread_dicts(body),
        "meta": _first(body.get("meta"), {}) or {},
    }
    upd = _first(body.get("update"))
    if upd:
        job_dict["update"] = _update_dict(upd)
    per = _first(body.get("periodic"))
    if per:
        job_dict["periodic"] = {
            "enabled": bool(per.get("enabled", True)),
            "spec": per.get("cron", per.get("spec", "")),
            "prohibit_overlap": bool(per.get("prohibit_overlap", False)),
        }
    par = _first(body.get("parameterized"))
    if par:
        job_dict["parameterized"] = {
            "payload": par.get("payload", ""),
            "meta_required": par.get("meta_required", []) or [],
            "meta_optional": par.get("meta_optional", []) or [],
        }
    pol = _first(body.get("policy"))
    if pol:
        job_dict["policy"] = {
            "throughput": {
                str(k): float(v)
                for k, v in (
                    _first(pol.get("throughput"), {}) or {}
                ).items()
            },
            "throughput_coefficient": float(
                pol.get("throughput_coefficient", 1.0)
            ),
            "migration_coefficient": float(
                pol.get("migration_coefficient", 0.0)
            ),
            "min_runtime_s": _duration_s(pol.get("min_runtime"), 0.0),
        }
    mr = _first(body.get("multiregion"))
    if mr:
        strat = _first(mr.get("strategy"), {}) or {}
        job_dict["multiregion"] = {
            "strategy": {
                "max_parallel": int(strat.get("max_parallel", 0)),
                "on_failure": strat.get("on_failure", ""),
            },
            "regions": [
                {
                    "name": r.get("__label__", r.get("name", "")),
                    "count": int(r.get("count", 0)),
                    "datacenters": r.get("datacenters", []) or [],
                    "meta": _first(r.get("meta"), {}) or {},
                }
                for r in _all(mr.get("region"))
            ],
        }
    return job_from_dict(job_dict)


def parse_file(path: str) -> Job:
    with open(path) as f:
        return parse(f.read())
