"""Scheduler helpers (reference scheduler/util.go)."""
from __future__ import annotations

from dataclasses import replace as _replace
from typing import Dict, List, Optional, Tuple, TYPE_CHECKING

from ..structs import (
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_DESIRED_STOP,
    Allocation,
    AllocMetric,
    AllocatedResources,
    AllocatedSharedResources,
    Evaluation,
    EVAL_STATUS_FAILED,
    Job,
    Node,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
    NODE_SCHED_ELIGIBLE,
    PlanResult,
    TaskGroup,
)
from .scheduler import SetStatusError

if TYPE_CHECKING:  # pragma: no cover
    from ..state.store import StateSnapshot
    from .context import EvalContext

ALLOC_IN_PLACE = "alloc updating in-place"


def ready_nodes_in_dcs(
    state: "StateSnapshot", datacenters: List[str]
) -> Tuple[List[Node], Dict[str, int]]:
    """(reference util.go:233 readyNodesInDCs)

    The scan is O(cluster); at 10k nodes it costs ~10ms of pure Python
    per evaluation, dwarfing the actual scheduling math.  Snapshots
    delegate node reads to the live store (mutation is serialized behind
    the plan applier), so the result is memoized on the store keyed by
    the nodes-table modify index + datacenter set; every caller —
    oracle scheduler, simulation pre-pass, prescore assembly — shares
    the hit.  Callers receive fresh list/dict copies (the stack shuffles
    its node list in place)."""
    store = getattr(state, "_store", None)
    if store is not None:
        key = (store.table_index("nodes"), tuple(datacenters))
        cache = getattr(store, "_ready_nodes_cache", None)
        if cache is None:
            cache = {}
            store._ready_nodes_cache = cache
        hit = cache.get(key)
        if hit is not None:
            return list(hit[0]), dict(hit[1])

    dc_map = {dc: 0 for dc in datacenters}
    out: List[Node] = []
    for node in state.nodes():
        if node.status != NODE_STATUS_READY:
            continue
        if node.drain:
            continue
        if node.scheduling_eligibility != NODE_SCHED_ELIGIBLE:
            continue
        if node.datacenter not in dc_map:
            continue
        out.append(node)
        dc_map[node.datacenter] += 1
    if store is not None:
        try:
            stale = bool(cache) and next(iter(cache))[0] != key[0]
        except (StopIteration, RuntimeError):
            # concurrent clear/insert from another scheduler thread
            stale = False
        if stale:
            cache.clear()
        cache[key] = (out, dc_map)
        return list(out), dict(dc_map)
    return out, dc_map


def tainted_nodes(
    state: "StateSnapshot", allocs: List[Allocation]
) -> Dict[str, Optional[Node]]:
    """Nodes (by id) whose allocs should migrate: down, draining, or gone
    (reference util.go:312 taintedNodes)."""
    out: Dict[str, Optional[Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = state.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.status == NODE_STATUS_DOWN or node.drain:
            out[alloc.node_id] = node
    return out


def retry_max(max_attempts: int, cb, reset=None) -> None:
    """(reference util.go:277 retryMax)"""
    attempts = 0
    while attempts < max_attempts:
        done = cb()
        if done:
            return
        if reset is not None and reset():
            attempts = 0
        else:
            attempts += 1
    raise SetStatusError(
        f"maximum attempts reached ({max_attempts})", EVAL_STATUS_FAILED
    )


def progress_made(result: Optional[PlanResult]) -> bool:
    """(reference util.go:303 progressMade)"""
    return result is not None and (
        bool(result.node_update)
        or bool(result.node_allocation)
        or result.deployment is not None
        or bool(result.deployment_updates)
    )


def update_non_terminal_allocs_to_lost(
    plan, tainted: Dict[str, Optional[Node]], allocs: List[Allocation]
) -> None:
    """Mark pending/running allocs on down nodes as lost
    (reference generic_sched.go:350 updateNonTerminalAllocsToLost)."""
    for alloc in allocs:
        node = tainted.get(alloc.node_id)
        if alloc.node_id not in tainted:
            continue
        if node is not None and node.status != NODE_STATUS_DOWN:
            continue
        if alloc.desired_status == ALLOC_DESIRED_STOP and alloc.client_status in (
            "running",
            "pending",
        ):
            plan.append_stopped_alloc(
                alloc,
                "alloc is lost since its node is down",
                ALLOC_CLIENT_STATUS_LOST,
            )


def _network_ports_map(net) -> Dict[str, int]:
    m = {}
    for p in net.reserved_ports:
        m[p.label] = p.value
    for p in net.dynamic_ports:
        m[p.label] = -1
    return m


def networks_updated(nets_a, nets_b) -> bool:
    if len(nets_a) != len(nets_b):
        return True
    for an, bn in zip(nets_a, nets_b):
        if an.mode != bn.mode or an.mbits != bn.mbits:
            return True
        if _network_ports_map(an) != _network_ports_map(bn):
            return True
    return False


def tasks_updated(job_a: Job, job_b: Job, task_group: str) -> bool:
    """In-place vs destructive diff (reference util.go:351 tasksUpdated)."""
    a = job_a.lookup_task_group(task_group)
    b = job_b.lookup_task_group(task_group)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if a.ephemeral_disk != b.ephemeral_disk:
        return True
    if networks_updated(a.networks, b.networks):
        return True
    if list(job_a.affinities) + list(a.affinities) != list(
        job_b.affinities
    ) + list(b.affinities):
        return True
    if list(job_a.spreads) + list(a.spreads) != list(job_b.spreads) + list(
        b.spreads
    ):
        return True
    b_tasks = {t.name: t for t in b.tasks}
    for at in a.tasks:
        bt = b_tasks.get(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver:
            return True
        if at.config != bt.config:
            return True
        if at.env != bt.env:
            return True
        if at.artifacts != bt.artifacts:
            return True
        if at.templates != bt.templates:
            return True
        if at.meta != bt.meta:
            return True
        if networks_updated(at.resources.networks, bt.resources.networks):
            return True
        if (
            at.resources.cpu != bt.resources.cpu
            or at.resources.memory_mb != bt.resources.memory_mb
            or at.resources.devices != bt.resources.devices
        ):
            return True
    return False


class AllocTuple:
    """(reference util.go:14 allocTuple)"""

    __slots__ = ("name", "task_group", "alloc")

    def __init__(self, name, task_group, alloc=None):
        self.name = name
        self.task_group = task_group
        self.alloc = alloc


class DiffResult:
    def __init__(self):
        self.place: List[AllocTuple] = []
        self.update: List[AllocTuple] = []
        self.migrate: List[AllocTuple] = []
        self.stop: List[AllocTuple] = []
        self.ignore: List[AllocTuple] = []
        self.lost: List[AllocTuple] = []

    def append(self, other: "DiffResult") -> None:
        self.place.extend(other.place)
        self.update.extend(other.update)
        self.migrate.extend(other.migrate)
        self.stop.extend(other.stop)
        self.ignore.extend(other.ignore)
        self.lost.extend(other.lost)


def materialize_task_groups(job: Job) -> Dict[str, TaskGroup]:
    """Expand tg.count into named alloc slots
    (reference util.go:21 materializeTaskGroups)."""
    out: Dict[str, TaskGroup] = {}
    if job.stopped():
        return out
    for tg in job.task_groups:
        for i in range(tg.count):
            out[f"{job.id}.{tg.name}[{i}]"] = tg
    return out


def diff_system_allocs_for_node(
    job: Job,
    node_id: str,
    eligible_nodes: Dict[str, Node],
    tainted: Dict[str, Optional[Node]],
    required: Dict[str, TaskGroup],
    allocs: List[Allocation],
    terminal_allocs: Dict[str, Allocation],
) -> DiffResult:
    """(reference util.go:70 diffSystemAllocsForNode)"""
    from ..structs import JOB_TYPE_BATCH

    result = DiffResult()
    existing = set()
    for exist in allocs:
        name = exist.name
        existing.add(name)
        tg = required.get(name)
        if tg is None:
            result.stop.append(AllocTuple(name, tg, exist))
            continue
        if (
            not exist.terminal_status()
            and exist.desired_transition.should_migrate()
        ):
            result.migrate.append(AllocTuple(name, tg, exist))
            continue
        if exist.node_id in tainted:
            node = tainted[exist.node_id]
            if (
                exist.job is not None
                and exist.job.type == JOB_TYPE_BATCH
                and exist.ran_successfully()
            ):
                result.ignore.append(AllocTuple(name, tg, exist))
                continue
            if not exist.terminal_status() and (
                node is None or node.terminal_status()
            ):
                result.lost.append(AllocTuple(name, tg, exist))
            else:
                result.ignore.append(AllocTuple(name, tg, exist))
            continue
        if node_id not in eligible_nodes:
            result.ignore.append(AllocTuple(name, tg, exist))
            continue
        if (
            exist.job is not None
            and job.job_modify_index != exist.job.job_modify_index
        ):
            result.update.append(AllocTuple(name, tg, exist))
            continue
        result.ignore.append(AllocTuple(name, tg, exist))

    for name, tg in required.items():
        if name in existing:
            continue
        if node_id in tainted:
            continue
        if node_id not in eligible_nodes:
            continue
        tup = AllocTuple(name, tg, terminal_allocs.get(name))
        if tup.alloc is None or tup.alloc.node_id != node_id:
            tup.alloc = Allocation(node_id=node_id)
        result.place.append(tup)
    return result


def diff_system_allocs(
    job: Job,
    nodes: List[Node],
    tainted: Dict[str, Optional[Node]],
    allocs: List[Allocation],
    terminal_allocs: Dict[str, Allocation],
) -> DiffResult:
    """(reference util.go:201 diffSystemAllocs)"""
    node_allocs: Dict[str, List[Allocation]] = {}
    for alloc in allocs:
        node_allocs.setdefault(alloc.node_id, []).append(alloc)
    eligible = {}
    for node in nodes:
        node_allocs.setdefault(node.id, [])
        eligible[node.id] = node
    required = materialize_task_groups(job)
    result = DiffResult()
    for node_id, nallocs in node_allocs.items():
        result.append(
            diff_system_allocs_for_node(
                job, node_id, eligible, tainted, required, nallocs,
                terminal_allocs,
            )
        )
    return result


def evict_and_place(
    ctx: "EvalContext",
    diff: DiffResult,
    allocs: List[AllocTuple],
    desc: str,
    limit_box: List[int],
) -> bool:
    """Evict each alloc and add to the place set, bounded by limit; returns
    True if the limit was reached (reference util.go evictAndPlace)."""
    n = len(allocs)
    for i in range(n):
        if limit_box[0] <= 0:
            return True
        a = allocs[i]
        ctx.plan.append_stopped_alloc(a.alloc, desc)
        diff.place.append(a)
        limit_box[0] -= 1
    return False


def inplace_update(
    ctx: "EvalContext",
    evaluation: Evaluation,
    job: Job,
    stack,
    updates: List[AllocTuple],
) -> Tuple[List[AllocTuple], List[AllocTuple]]:
    """Attempt in-place updates; returns (destructive, inplace)
    (reference util.go:556 inplaceUpdate)."""
    inplace_count = 0
    destructive: List[AllocTuple] = []
    inplace: List[AllocTuple] = []
    for update in updates:
        existing = update.alloc
        if existing.job is not None and tasks_updated(
            job, existing.job, update.task_group.name
        ):
            destructive.append(update)
            continue
        node = ctx.state.node_by_id(existing.node_id)
        if node is None:
            destructive.append(update)
            continue
        stack.set_nodes([node])
        ctx.plan.append_stopped_alloc(existing, ALLOC_IN_PLACE)
        option = stack.select(update.task_group, None)
        node_updates = ctx.plan.node_update.get(existing.node_id, [])
        ctx.plan.node_update[existing.node_id] = [
            a for a in node_updates if a.id != existing.id
        ]
        if not ctx.plan.node_update[existing.node_id]:
            del ctx.plan.node_update[existing.node_id]
        if option is None:
            destructive.append(update)
            continue
        new_alloc = _replace(existing)
        new_alloc.eval_id = evaluation.id
        new_alloc.allocated_resources = AllocatedResources(
            tasks=option.task_resources,
            shared=AllocatedSharedResources(
                disk_mb=update.task_group.ephemeral_disk.size_mb
            ),
        )
        if existing.allocated_resources is not None:
            new_alloc.allocated_resources.shared.networks = (
                existing.allocated_resources.shared.networks
            )
            new_alloc.allocated_resources.shared.ports = (
                existing.allocated_resources.shared.ports
            )
        ctx.plan.append_alloc(new_alloc)
        inplace.append(update)
        inplace_count += 1
    return destructive, inplace


def generic_alloc_update_fn(ctx: "EvalContext", stack, eval_id: str):
    """Factory for the reconciler's inplace/destructive decision
    (reference util.go:849 genericAllocUpdateFn)."""

    def update_fn(
        existing: Allocation, new_job: Job, new_tg: TaskGroup
    ) -> Tuple[bool, bool, Optional[Allocation]]:
        if (
            existing.job is not None
            and existing.job.job_modify_index == new_job.job_modify_index
        ):
            return True, False, None
        if existing.job is not None and tasks_updated(
            new_job, existing.job, new_tg.name
        ):
            return False, True, None
        if existing.terminal_status():
            return True, False, None

        node = ctx.state.node_by_id(existing.node_id)
        if node is None:
            return False, True, None

        stack.set_nodes([node])
        ctx.plan.append_stopped_alloc(existing, ALLOC_IN_PLACE)
        option = stack.select(new_tg, None)
        # pop the staged eviction
        updates = ctx.plan.node_update.get(existing.node_id, [])
        ctx.plan.node_update[existing.node_id] = [
            a for a in updates if a.id != existing.id
        ]
        if not ctx.plan.node_update[existing.node_id]:
            del ctx.plan.node_update[existing.node_id]

        if option is None:
            return False, True, None

        # restore network/device offers from the existing allocation
        for task_name, resources in option.task_resources.items():
            if existing.allocated_resources is not None:
                tr = existing.allocated_resources.tasks.get(task_name)
                if tr is not None:
                    resources.networks = tr.networks
                    resources.devices = tr.devices

        new_alloc = _replace(existing)
        new_alloc.eval_id = eval_id
        new_alloc.allocated_resources = AllocatedResources(
            tasks=option.task_resources,
            shared=AllocatedSharedResources(
                disk_mb=new_tg.ephemeral_disk.size_mb
            ),
        )
        if existing.allocated_resources is not None:
            new_alloc.allocated_resources.shared.networks = (
                existing.allocated_resources.shared.networks
            )
            new_alloc.allocated_resources.shared.ports = (
                existing.allocated_resources.shared.ports
            )
        new_alloc.metrics = existing.metrics
        return False, False, new_alloc

    return update_fn


def set_status(
    planner,
    evaluation: Evaluation,
    next_eval: Optional[Evaluation],
    spawned_blocked: Optional[Evaluation],
    tg_metrics: Optional[Dict[str, AllocMetric]],
    status: str,
    description: str,
    queued_allocs: Optional[Dict[str, int]],
    deployment_id: str,
) -> None:
    """(reference util.go:530 setStatus)"""
    new_eval = _replace(evaluation)
    new_eval.status = status
    new_eval.status_description = description
    new_eval.deployment_id = deployment_id
    new_eval.failed_tg_allocs = tg_metrics or {}
    if next_eval is not None:
        new_eval.next_eval = next_eval.id
    if spawned_blocked is not None:
        new_eval.blocked_eval = spawned_blocked.id
    if queued_allocs is not None:
        new_eval.queued_allocations = dict(queued_allocs)
    planner.update_eval(new_eval)


def adjust_queued_allocations(
    result: Optional[PlanResult], queued: Dict[str, int]
) -> None:
    """Decrement queued counts by successfully-placed allocs
    (reference util.go adjustQueuedAllocations)."""
    if result is None:
        return
    for allocs in result.node_allocation.values():
        for alloc in allocs:
            # only count newly created allocs (create index matches the
            # plan-apply index), not in-place updates
            if alloc.create_index != result.alloc_index:
                continue
            if alloc.task_group in queued:
                queued[alloc.task_group] -= 1
