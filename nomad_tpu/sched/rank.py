"""Ranking iterators: the host-side oracle scoring chain
(reference scheduler/rank.go).

Score-append semantics matter for parity with the vectorized kernel: each
iterator appends to ``RankedNode.scores`` only under specific conditions
(binpack always; device affinity only when device affinities exist;
job-anti-affinity only on collisions; rescheduling penalty only on penalty
nodes; node affinity only when the total is non-zero; spread only when the
boost is non-zero; preemption only when allocs would be preempted) and the
final score is the *mean of appended scores* (rank.go:696
ScoreNormalizationIterator).  The kernel reproduces exactly this
sum/count arithmetic (ops/score.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs import (
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    Job,
    NetworkIndex,
    NetworkResource,
    Node,
    TaskGroup,
    allocs_fit,
    remove_allocs,
    score_fit_binpack,
    score_fit_spread,
    SCHEDULER_ALGORITHM_SPREAD,
)
from ..structs.funcs import (
    BINPACK_MAX_FIT_SCORE,
    net_priority,
    preemption_score,
)
from .context import EvalContext
from .device import DeviceAllocator
from .feasible import resolve_target
from .operators import check_affinity
from .preemption import Preemptor


@dataclass
class RankedNode:
    """(reference rank.go:19)"""

    node: Node
    final_score: float = 0.0
    scores: List[float] = field(default_factory=list)
    task_resources: Dict[str, AllocatedTaskResources] = field(
        default_factory=dict
    )
    alloc_resources: Optional[AllocatedSharedResources] = None
    proposed: Optional[List[Allocation]] = None
    preempted_allocs: Optional[List[Allocation]] = None

    def proposed_allocs(self, ctx: EvalContext) -> List[Allocation]:
        if self.proposed is None:
            self.proposed = ctx.proposed_allocs(self.node.id)
        return self.proposed

    def set_task_resources(
        self, task, resources: AllocatedTaskResources
    ) -> None:
        self.task_resources[task.name] = resources


class FeasibleRankIterator:
    """(reference rank.go:76)"""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        return RankedNode(node=option)

    def reset(self) -> None:
        self.source.reset()


class StaticRankIterator:
    """Fixed list of ranked nodes; testing aid (reference rank.go:105)."""

    def __init__(self, ctx: EvalContext, nodes: List[RankedNode]) -> None:
        self.ctx = ctx
        self.nodes = nodes
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[RankedNode]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        return option

    def reset(self) -> None:
        self.seen = 0


class BinPackIterator:
    """Resource fitting + fitness scoring, with optional preemption
    (reference rank.go:149)."""

    def __init__(
        self,
        ctx: EvalContext,
        source,
        evict: bool,
        priority: int,
        algorithm: str,
    ) -> None:
        self.ctx = ctx
        self.source = source
        self.evict = evict
        self.priority = priority
        self.job_ns_id: Tuple[str, str] = ("", "")
        self.task_group: Optional[TaskGroup] = None
        self.score_fit = (
            score_fit_spread
            if algorithm == SCHEDULER_ALGORITHM_SPREAD
            else score_fit_binpack
        )

    def set_job(self, job: Job) -> None:
        self.priority = job.priority
        self.job_ns_id = job.namespaced_id()

    def set_task_group(self, tg: TaskGroup) -> None:
        self.task_group = tg

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None:
                return None

            tg = self.task_group
            proposed = option.proposed_allocs(self.ctx)

            net_idx = NetworkIndex()
            net_idx.set_node(option.node)
            net_idx.add_allocs(proposed)

            dev_allocator = DeviceAllocator(self.ctx, option.node)
            dev_allocator.add_allocs(proposed)

            total_device_affinity_weight = 0.0
            sum_matching_affinities = 0.0

            total = AllocatedResources(
                shared=AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb
                )
            )

            allocs_to_preempt: List[Allocation] = []
            preemptor = Preemptor(self.priority, self.job_ns_id)
            preemptor.set_node(option.node)
            current_preemptions = [
                alloc
                for allocs in self.ctx.plan.node_preemptions.values()
                for alloc in allocs
            ]
            preemptor.set_preemptions(current_preemptions)

            # group-level network ask (reference rank.go:240)
            if tg.networks:
                ask = tg.networks[0].copy()
                offer = net_idx.assign_ports(ask)
                if offer is None:
                    if not self.evict:
                        self.ctx.metrics.exhausted_node(
                            option.node, "network: port collision"
                        )
                        continue
                    preemptor.set_candidates(proposed)
                    net_preemptions = preemptor.preempt_for_network(
                        ask, net_idx
                    )
                    if net_preemptions is None:
                        continue
                    allocs_to_preempt.extend(net_preemptions)
                    proposed = remove_allocs(proposed, net_preemptions)
                    net_idx = NetworkIndex()
                    net_idx.set_node(option.node)
                    net_idx.add_allocs(proposed)
                    offer = net_idx.assign_ports(ask)
                    if offer is None:
                        continue
                net_idx.add_reserved_ports(offer)
                nw_res = NetworkResource(
                    mode=ask.mode, mbits=ask.mbits
                )
                total.shared.networks = [nw_res]
                total.shared.ports = offer
                option.alloc_resources = AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb,
                    networks=[nw_res],
                    ports=offer,
                )

            exhausted = False
            for task in tg.tasks:
                task_resources = AllocatedTaskResources(
                    cpu=task.resources.cpu,
                    memory_mb=task.resources.memory_mb,
                )

                # task-level network ask (reference rank.go:302)
                if task.resources.networks:
                    ask = task.resources.networks[0].copy()
                    offer_net = net_idx.assign_network(ask)
                    if offer_net is None:
                        if not self.evict:
                            self.ctx.metrics.exhausted_node(
                                option.node, "network: port collision"
                            )
                            exhausted = True
                            break
                        preemptor.set_candidates(proposed)
                        net_preemptions = preemptor.preempt_for_network(
                            ask, net_idx
                        )
                        if net_preemptions is None:
                            exhausted = True
                            break
                        allocs_to_preempt.extend(net_preemptions)
                        proposed = remove_allocs(proposed, net_preemptions)
                        net_idx = NetworkIndex()
                        net_idx.set_node(option.node)
                        net_idx.add_allocs(proposed)
                        offer_net = net_idx.assign_network(ask)
                        if offer_net is None:
                            exhausted = True
                            break
                    net_idx.add_reserved(offer_net)
                    task_resources.networks = [offer_net]

                # device asks (reference rank.go:360)
                for req in task.resources.devices:
                    offer_dev, sum_affinities, err = (
                        dev_allocator.assign_device(req)
                    )
                    if offer_dev is None:
                        if not self.evict:
                            self.ctx.metrics.exhausted_node(
                                option.node, f"devices: {err}"
                            )
                            exhausted = True
                            break
                        preemptor.set_candidates(proposed)
                        device_preemptions = preemptor.preempt_for_device(
                            req, dev_allocator
                        )
                        if device_preemptions is None:
                            exhausted = True
                            break
                        allocs_to_preempt.extend(device_preemptions)
                        proposed = remove_allocs(proposed, allocs_to_preempt)
                        dev_allocator = DeviceAllocator(self.ctx, option.node)
                        dev_allocator.add_allocs(proposed)
                        offer_dev, sum_affinities, err = (
                            dev_allocator.assign_device(req)
                        )
                        if offer_dev is None:
                            exhausted = True
                            break
                    dev_allocator.add_reserved(offer_dev)
                    task_resources.devices.append(offer_dev)
                    if req.affinities:
                        for aff in req.affinities:
                            total_device_affinity_weight += abs(
                                float(aff.weight)
                            )
                        sum_matching_affinities += sum_affinities
                if exhausted:
                    break

                option.set_task_resources(task, task_resources)
                total.tasks[task.name] = task_resources
            if exhausted:
                continue

            current = proposed
            probe = Allocation(allocated_resources=total)
            proposed = proposed + [probe]

            fit, dim, util = allocs_fit(option.node, proposed, net_idx, False)
            if not fit:
                if not self.evict:
                    self.ctx.metrics.exhausted_node(option.node, dim)
                    continue
                preemptor.set_candidates(current)
                preempted = preemptor.preempt_for_task_group(total)
                allocs_to_preempt.extend(preempted)
                if not preempted:
                    self.ctx.metrics.exhausted_node(option.node, dim)
                    continue
            if allocs_to_preempt:
                option.preempted_allocs = allocs_to_preempt

            fitness = self.score_fit(option.node, util)
            normalized = fitness / BINPACK_MAX_FIT_SCORE
            option.scores.append(normalized)
            self.ctx.metrics.score_node(option.node, "binpack", normalized)

            if total_device_affinity_weight != 0:
                sum_matching_affinities /= total_device_affinity_weight
                option.scores.append(sum_matching_affinities)
                self.ctx.metrics.score_node(
                    option.node, "devices", sum_matching_affinities
                )
            return option

    def reset(self) -> None:
        self.source.reset()


class JobAntiAffinityIterator:
    """Penalty for co-locating allocs of the same job+group
    (reference rank.go:474): -(collisions+1)/desired_count, appended only
    when collisions > 0."""

    def __init__(self, ctx: EvalContext, source, job_id: str) -> None:
        self.ctx = ctx
        self.source = source
        self.job_id = job_id
        self.task_group = ""
        self.desired_count = 0

    def set_job(self, job: Job) -> None:
        self.job_id = job.id

    def set_task_group(self, tg: TaskGroup) -> None:
        self.task_group = tg.name
        self.desired_count = tg.count

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None:
                return None
            proposed = option.proposed_allocs(self.ctx)
            collisions = sum(
                1
                for alloc in proposed
                if alloc.job_id == self.job_id
                and alloc.task_group == self.task_group
            )
            if collisions > 0:
                penalty = -1.0 * float(collisions + 1) / float(
                    self.desired_count
                )
                option.scores.append(penalty)
                self.ctx.metrics.score_node(
                    option.node, "job-anti-affinity", penalty
                )
            else:
                self.ctx.metrics.score_node(
                    option.node, "job-anti-affinity", 0
                )
            return option

    def reset(self) -> None:
        self.source.reset()


class NodeReschedulingPenaltyIterator:
    """-1 on nodes where a previous attempt of the alloc failed
    (reference rank.go:544)."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.penalty_nodes: set = set()

    def set_penalty_nodes(self, penalty_nodes) -> None:
        self.penalty_nodes = set(penalty_nodes or ())

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        if option.node.id in self.penalty_nodes:
            option.scores.append(-1.0)
            self.ctx.metrics.score_node(
                option.node, "node-reschedule-penalty", -1
            )
        else:
            self.ctx.metrics.score_node(
                option.node, "node-reschedule-penalty", 0
            )
        return option

    def reset(self) -> None:
        self.penalty_nodes = set()
        self.source.reset()


class NodeAffinityIterator:
    """Weighted affinity score: sum(matched weights)/sum(|weights|),
    appended only when non-zero (reference rank.go:589)."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.job_affinities: List = []
        self.affinities: List = []

    def set_job(self, job: Job) -> None:
        self.job_affinities = list(job.affinities)

    def set_task_group(self, tg: TaskGroup) -> None:
        if self.job_affinities:
            self.affinities.extend(self.job_affinities)
        if tg.affinities:
            self.affinities.extend(tg.affinities)
        for task in tg.tasks:
            if task.affinities:
                self.affinities.extend(task.affinities)

    def has_affinities(self) -> bool:
        return bool(self.affinities)

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        if not self.has_affinities():
            self.ctx.metrics.score_node(option.node, "node-affinity", 0)
            return option
        sum_weight = sum(abs(float(a.weight)) for a in self.affinities)
        total = 0.0
        for aff in self.affinities:
            if self._matches(aff, option.node):
                total += float(aff.weight)
        norm_score = total / sum_weight
        if total != 0.0:
            option.scores.append(norm_score)
            self.ctx.metrics.score_node(
                option.node, "node-affinity", norm_score
            )
        return option

    def _matches(self, affinity, node: Node) -> bool:
        lval, lok = resolve_target(affinity.ltarget, node)
        rval, rok = resolve_target(affinity.rtarget, node)
        return check_affinity(
            affinity.operand,
            lval,
            rval,
            lok,
            rok,
            self.ctx.regex_cache,
            self.ctx.version_cache,
        )

    def reset(self) -> None:
        self.source.reset()
        self.affinities = []


class ScoreNormalizationIterator:
    """final_score = mean(scores) (reference rank.go:679)."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not option.scores:
            return option
        option.final_score = sum(option.scores) / float(len(option.scores))
        self.ctx.metrics.score_node(
            option.node, "normalized-score", option.final_score
        )
        return option

    def reset(self) -> None:
        self.source.reset()


class PolicyIterator:
    """Policy-weighted scoring, the serial oracle half (sched/policy.py
    holds the shared resolution/assembly; ops/score.py the fused kernel
    terms).  Sits between SpreadIterator and PreemptionScoringIterator
    so the policy terms append LAST among the soft scores — the same
    left-to-right float-sum position the kernel fuses them at.

    Append conventions mirror the kernel bit-for-bit: the throughput
    term appends for EVERY node when the policy carries a throughput
    table (zeros included — binpack convention); the migration term is
    a penalty on non-incumbent nodes, appended only where non-zero
    (node-reschedule-penalty convention, recorded as 0 elsewhere like
    job-anti-affinity)."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.job: Optional[Job] = None
        self.resolved = None
        self.tg_name = ""
        self.sticky: set = set()

    def set_job(self, job: Job) -> None:
        from .policy import resolve

        self.job = job
        self.resolved = resolve(job)

    def set_task_group(self, tg: TaskGroup) -> None:
        from .policy import sticky_node_ids

        self.tg_name = tg.name
        if self.resolved is not None:
            self.sticky = sticky_node_ids(
                self.resolved, self.job, tg.name, self.ctx.state
            )
        else:
            self.sticky = set()

    def has_policy(self) -> bool:
        return self.resolved is not None

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or self.resolved is None:
            return option
        pol = self.resolved
        if pol.has_tput:
            value = pol.tput_coef * pol.tput_value(
                option.node.node_class
            )
            option.scores.append(value)
            self.ctx.metrics.score_node(
                option.node, "policy.throughput", value
            )
        # penalty shape (see policy.migration_vector): non-incumbent
        # nodes pay -coef, the incumbent's mean stays untouched; inert
        # when the TG has no live allocs
        mig = 0.0
        if self.sticky:
            mig = pol.mig_coef * (
                0.0 if option.node.id in self.sticky else -1.0
            )
        if mig != 0.0:
            option.scores.append(mig)
            self.ctx.metrics.score_node(
                option.node, "policy.migration", mig
            )
        elif pol.mig_coef != 0.0:
            self.ctx.metrics.score_node(
                option.node, "policy.migration", 0
            )
        return option

    def reset(self) -> None:
        self.source.reset()


class PreemptionScoringIterator:
    """Logistic net-priority score when the placement would preempt
    (reference rank.go:714)."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or option.preempted_allocs is None:
            return option
        priorities = [
            alloc.job.priority
            for alloc in option.preempted_allocs
            if alloc.job is not None
        ]
        netp = net_priority(priorities)
        score = preemption_score(netp)
        option.scores.append(score)
        self.ctx.metrics.score_node(option.node, "preemption", score)
        return option

    def reset(self) -> None:
        self.source.reset()
