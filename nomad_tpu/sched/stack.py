"""Placement stacks (reference scheduler/stack.go).

`GenericStack` wires the oracle iterator chain in the reference's exact
order (stack.go:321 NewGenericStack): shuffled source -> feasibility
wrapper (job constraints; drivers, tg constraints, host volumes, devices,
network; CSI availability) -> distinct hosts/property -> binpack ->
job-anti-affinity -> rescheduling penalty -> node affinity -> spread ->
preemption scoring -> normalization -> limit -> max score.

`TPUGenericStack` (tpu_stack.py) implements the same `select` surface on
the vectorized kernel; either can back the generic/system schedulers.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..structs import Job, Node, TaskGroup
from .context import EvalContext
from .feasible import (
    ConstraintChecker,
    CSIVolumeChecker,
    DeviceChecker,
    DistinctHostsIterator,
    DistinctPropertyIterator,
    DriverChecker,
    FeasibilityWrapper,
    HostVolumeChecker,
    NetworkChecker,
    StaticIterator,
    new_random_iterator,
    shuffle_nodes,
)
from .rank import (
    BinPackIterator,
    FeasibleRankIterator,
    JobAntiAffinityIterator,
    NodeAffinityIterator,
    NodeReschedulingPenaltyIterator,
    PolicyIterator,
    PreemptionScoringIterator,
    RankedNode,
    ScoreNormalizationIterator,
)
from .select import LimitIterator, MaxScoreIterator
from .spread import SpreadIterator

# (reference stack.go:10-18)
SKIP_SCORE_THRESHOLD = 0.0
MAX_SKIP = 3


@dataclass
class SelectOptions:
    """(reference stack.go:34)"""

    penalty_node_ids: Set[str] = field(default_factory=set)
    preferred_nodes: List[Node] = field(default_factory=list)
    preempt: bool = False


def task_group_constraints(tg: TaskGroup):
    """Merge task-group + task constraints and collect drivers
    (reference scheduler/util.go taskGroupConstraints)."""
    constraints = list(tg.constraints)
    drivers = set()
    for task in tg.tasks:
        drivers.add(task.driver)
        constraints.extend(task.constraints)
    return constraints, drivers


def compute_visit_limit(n_nodes: int, batch: bool) -> int:
    """Power-of-two-choices limit: 2 for batch, max(2, ceil(log2 N)) for
    service (reference stack.go:77-89)."""
    limit = 2
    if not batch and n_nodes > 0:
        log_limit = int(math.ceil(math.log2(n_nodes)))
        if log_limit > limit:
            limit = log_limit
    return limit


class GenericStack:
    def __init__(self, batch: bool, ctx: EvalContext) -> None:
        self.batch = batch
        self.ctx = ctx
        self.job_version: Optional[int] = None

        self.source = StaticIterator(ctx, [])

        self.job_constraint = ConstraintChecker(ctx, [])
        self.task_group_drivers = DriverChecker(ctx)
        self.task_group_constraint = ConstraintChecker(ctx, [])
        self.task_group_devices = DeviceChecker(ctx)
        self.task_group_host_volumes = HostVolumeChecker(ctx)
        self.task_group_csi_volumes = CSIVolumeChecker(ctx)
        self.task_group_network = NetworkChecker(ctx)

        self.wrapped_checks = FeasibilityWrapper(
            ctx,
            self.source,
            job_checkers=[self.job_constraint],
            tg_checkers=[
                self.task_group_drivers,
                self.task_group_constraint,
                self.task_group_host_volumes,
                self.task_group_devices,
                self.task_group_network,
            ],
            tg_available=[self.task_group_csi_volumes],
        )
        self.distinct_hosts_constraint = DistinctHostsIterator(
            ctx, self.wrapped_checks
        )
        self.distinct_property_constraint = DistinctPropertyIterator(
            ctx, self.distinct_hosts_constraint
        )
        rank_source = FeasibleRankIterator(
            ctx, self.distinct_property_constraint
        )
        algorithm = (
            ctx.state.scheduler_config().effective_scheduler_algorithm()
        )
        self.bin_pack = BinPackIterator(ctx, rank_source, False, 0, algorithm)
        self.job_anti_aff = JobAntiAffinityIterator(ctx, self.bin_pack, "")
        self.node_rescheduling_penalty = NodeReschedulingPenaltyIterator(
            ctx, self.job_anti_aff
        )
        self.node_affinity = NodeAffinityIterator(
            ctx, self.node_rescheduling_penalty
        )
        self.spread = SpreadIterator(ctx, self.node_affinity)
        # policy-weighted scoring appends AFTER spread so the terms
        # land last in the left-to-right float sum, matching the
        # kernel's fusion point (ops/score.py PolicyTerms)
        self.policy = PolicyIterator(ctx, self.spread)
        preemption_scorer = PreemptionScoringIterator(ctx, self.policy)
        self.score_norm = ScoreNormalizationIterator(ctx, preemption_scorer)
        self.limit = LimitIterator(
            ctx, self.score_norm, 2, SKIP_SCORE_THRESHOLD, MAX_SKIP
        )
        self.max_score = MaxScoreIterator(ctx, self.limit)

    def set_nodes(self, base_nodes: List[Node]) -> None:
        nodes = list(base_nodes)
        shuffle_nodes(self.ctx.rng, nodes)
        self.source.set_nodes(nodes)
        self.limit.set_limit(compute_visit_limit(len(nodes), self.batch))

    def set_job(self, job: Job) -> None:
        if self.job_version is not None and self.job_version == job.version:
            return
        self.job_version = job.version
        self.job_constraint.set_constraints(job.constraints)
        self.task_group_csi_volumes.set_namespace(job.namespace)
        self.distinct_hosts_constraint.set_job(job)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_job(job)
        self.job_anti_aff.set_job(job)
        self.node_affinity.set_job(job)
        self.spread.set_job(job)
        self.policy.set_job(job)
        self.ctx.eligibility.set_job(job)

    def select(
        self, tg: TaskGroup, options: Optional[SelectOptions] = None
    ) -> Optional[RankedNode]:
        # preferred-node pass (sticky ephemeral disk, stack.go:119)
        if options is not None and options.preferred_nodes:
            original_nodes = self.source.nodes
            self.source.set_nodes(list(options.preferred_nodes))
            options_new = SelectOptions(
                penalty_node_ids=options.penalty_node_ids,
                preferred_nodes=[],
                preempt=options.preempt,
            )
            option = self.select(tg, options_new)
            self.source.set_nodes(original_nodes)
            if option is not None:
                return option
            return self.select(tg, options_new)

        self.max_score.reset()
        self.ctx.reset()

        constraints, drivers = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(drivers)
        self.task_group_constraint.set_constraints(constraints)
        self.task_group_devices.set_task_group(tg)
        self.task_group_host_volumes.set_volumes(tg.volumes)
        self.task_group_csi_volumes.set_volumes(tg.volumes)
        if tg.networks:
            self.task_group_network.set_network(tg.networks[0])
        self.distinct_hosts_constraint.set_task_group(tg)
        self.distinct_property_constraint.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.bin_pack.set_task_group(tg)
        if options is not None:
            self.bin_pack.evict = options.preempt
            self.node_rescheduling_penalty.set_penalty_nodes(
                options.penalty_node_ids
            )
        self.job_anti_aff.set_task_group(tg)
        self.node_affinity.set_task_group(tg)
        self.spread.set_task_group(tg)
        self.policy.set_task_group(tg)

        # policy joins affinity/spread in the "scoring is not purely
        # random" unlimited-walk rule: weighted scores must survey the
        # whole candidate set (tpu_stack and storm staging apply the
        # same rule so the kernel walk stays bit-identical)
        if (
            self.node_affinity.has_affinities()
            or self.spread.has_spreads()
            or self.policy.has_policy()
        ):
            self.limit.set_limit(2**31 - 1)

        return self.max_score.next()


class SystemStack:
    """Linear source, no spread/affinity/limit; preemption on by default
    per scheduler config (reference stack.go:182-318)."""

    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.source = StaticIterator(ctx, [])

        self.job_constraint = ConstraintChecker(ctx, [])
        self.task_group_drivers = DriverChecker(ctx)
        self.task_group_constraint = ConstraintChecker(ctx, [])
        self.task_group_devices = DeviceChecker(ctx)
        self.task_group_host_volumes = HostVolumeChecker(ctx)
        self.task_group_csi_volumes = CSIVolumeChecker(ctx)
        self.task_group_network = NetworkChecker(ctx)

        self.wrapped_checks = FeasibilityWrapper(
            ctx,
            self.source,
            job_checkers=[self.job_constraint],
            tg_checkers=[
                self.task_group_drivers,
                self.task_group_constraint,
                self.task_group_host_volumes,
                self.task_group_devices,
                self.task_group_network,
            ],
            tg_available=[self.task_group_csi_volumes],
        )
        self.distinct_property_constraint = DistinctPropertyIterator(
            ctx, self.wrapped_checks
        )
        rank_source = FeasibleRankIterator(
            ctx, self.distinct_property_constraint
        )
        config = ctx.state.scheduler_config()
        enable_preemption = (
            config.preemption_config.system_scheduler_enabled
        )
        algorithm = config.effective_scheduler_algorithm()
        self.bin_pack = BinPackIterator(
            ctx, rank_source, enable_preemption, 0, algorithm
        )
        preemption_scorer = PreemptionScoringIterator(ctx, self.bin_pack)
        self.score_norm = ScoreNormalizationIterator(ctx, preemption_scorer)

    def set_nodes(self, base_nodes: List[Node]) -> None:
        self.source.set_nodes(list(base_nodes))

    def set_job(self, job: Job) -> None:
        self.job_constraint.set_constraints(job.constraints)
        self.task_group_csi_volumes.set_namespace(job.namespace)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_job(job)
        self.ctx.eligibility.set_job(job)

    def select(
        self, tg: TaskGroup, options: Optional[SelectOptions] = None
    ) -> Optional[RankedNode]:
        self.ctx.reset()

        constraints, drivers = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(drivers)
        self.task_group_constraint.set_constraints(constraints)
        self.task_group_devices.set_task_group(tg)
        self.task_group_host_volumes.set_volumes(tg.volumes)
        self.task_group_csi_volumes.set_volumes(tg.volumes)
        if tg.networks:
            self.task_group_network.set_network(tg.networks[0])
        self.distinct_property_constraint.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.bin_pack.set_task_group(tg)

        return self.score_norm.next()
