"""Per-evaluation scratch state (reference scheduler/context.go).

`EvalContext` carries the in-flight plan, placement metrics, the
proposed-allocation view (state allocs minus plan evictions/preemptions
plus plan placements, context.go:120 ProposedAllocs), computed-class
eligibility memoization (context.go:190 EvalEligibility), operator caches
and the seeded RNG that replaces the reference's global `rand` so both the
oracle chain and the TPU kernel walk nodes in the same shuffled order
(SURVEY.md section 7.3 determinism note).
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, TYPE_CHECKING

from ..structs import (
    Allocation,
    AllocMetric,
    Job,
    Plan,
    escaped_constraints,
)
from ..structs.node_class import escaped_constraints as _escaped

if TYPE_CHECKING:  # pragma: no cover
    from ..state.store import StateSnapshot

# Computed-class feasibility states (reference context.go:167-186)
CLASS_UNKNOWN = 0
CLASS_INELIGIBLE = 1
CLASS_ELIGIBLE = 2
CLASS_ESCAPED = 3


class EvalEligibility:
    """Tracks per-computed-class feasibility over an evaluation
    (reference context.go:190)."""

    def __init__(self) -> None:
        self.job: Dict[str, int] = {}
        self.job_escaped = False
        self.task_groups: Dict[str, Dict[str, int]] = {}
        self.tg_escaped: Dict[str, bool] = {}
        self.quota_reached = ""

    def set_job(self, job: Job) -> None:
        escaped = bool(_escaped(job.constraints))
        for tg in job.task_groups:
            constraints = list(tg.constraints)
            for task in tg.tasks:
                constraints.extend(task.constraints)
            self.tg_escaped[tg.name] = bool(_escaped(constraints))
        self.job_escaped = escaped

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped.values())

    def job_status(self, klass: str) -> int:
        if self.job_escaped:
            return CLASS_ESCAPED
        if not klass:
            return CLASS_ESCAPED
        return self.job.get(klass, CLASS_UNKNOWN)

    def set_job_eligibility(self, eligible: bool, klass: str) -> None:
        self.job[klass] = CLASS_ELIGIBLE if eligible else CLASS_INELIGIBLE

    def task_group_status(self, tg: str, klass: str) -> int:
        if self.tg_escaped.get(tg, False):
            return CLASS_ESCAPED
        if not klass:
            return CLASS_ESCAPED
        return self.task_groups.get(tg, {}).get(klass, CLASS_UNKNOWN)

    def set_task_group_eligibility(
        self, eligible: bool, tg: str, klass: str
    ) -> None:
        self.task_groups.setdefault(tg, {})[klass] = (
            CLASS_ELIGIBLE if eligible else CLASS_INELIGIBLE
        )

    def get_classes(self) -> Dict[str, bool]:
        """Flatten job+tg eligibility into class -> eligible, for blocked
        evals (reference context.go GetClasses)."""
        out: Dict[str, bool] = {}
        for klass, status in self.job.items():
            if status == CLASS_ELIGIBLE:
                out[klass] = True
            elif status == CLASS_INELIGIBLE:
                out[klass] = False
        elig: Dict[str, bool] = {}
        for tg_classes in self.task_groups.values():
            for klass, status in tg_classes.items():
                if status == CLASS_ELIGIBLE:
                    elig[klass] = True
                elif status == CLASS_INELIGIBLE and klass not in out:
                    out.setdefault(klass, False)
        out.update(elig)
        return out


class EvalContext:
    def __init__(
        self,
        state: "StateSnapshot",
        plan: Plan,
        seed: Optional[int] = None,
        speculative: bool = False,
    ) -> None:
        self.state = state
        self.plan = plan
        self._metric_seq = 0
        self.metrics = AllocMetric()
        self.eligibility = EvalEligibility()
        self.regex_cache: Dict = {}
        self.version_cache: Dict = {}
        self.rng = random.Random(seed)
        # speculative replay mode (BatchWorker optimistic parallel
        # replay): this context is pinned to a wave snapshot and runs
        # concurrently with other evals' replays, so stack paths whose
        # read set can't be conflict-checked per node (preemption
        # passthrough walks EVERY candidate) must deviate to the
        # serial path instead of answering from possibly-stale state
        self.speculative = speculative

    def reset(self) -> None:
        """Called between placements (reference context.go:116 Reset)."""
        self._metric_seq += 1
        self.metrics = AllocMetric(seq=self._metric_seq)

    def proposed_allocs(self, node_id: str) -> List[Allocation]:
        """(reference context.go:120 ProposedAllocs)"""
        proposed = self.state.allocs_by_node_terminal(node_id, False)

        update = self.plan.node_update.get(node_id)
        if update:
            drop = {a.id for a in update}
            proposed = [a for a in proposed if a.id not in drop]

        preempted = self.plan.node_preemptions.get(node_id)
        if preempted:
            drop = {a.id for a in preempted}
            proposed = [a for a in proposed if a.id not in drop]

        by_id = {a.id: a for a in proposed}
        for alloc in self.plan.node_allocation.get(node_id, ()):
            by_id[alloc.id] = alloc
        return list(by_id.values())


__all__ = [
    "EvalContext",
    "EvalEligibility",
    "CLASS_UNKNOWN",
    "CLASS_INELIGIBLE",
    "CLASS_ELIGIBLE",
    "CLASS_ESCAPED",
    "escaped_constraints",
]
