"""Device instance assignment with affinity scoring
(reference scheduler/device.go).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..structs import (
    AllocatedDeviceResource,
    Allocation,
    Node,
    RequestedDevice,
)
from ..structs.device_accounting import DeviceAccounter
from .feasible import _resolve_device_target
from .operators import check_affinity


def matched_affinity_weight(
    group, affinities, regex_cache, version_cache
) -> Tuple[float, float]:
    """(total |weight|, matched weight sum) of a device ask's
    affinities against one device group (reference device.go:75-90) —
    THE single implementation, shared by the sequential allocator and
    the batch prescorer's static score column so the two can never
    desynchronize."""
    total = 0.0
    matched = 0.0
    for aff in affinities:
        lval, lok = _resolve_device_target(aff.ltarget, group)
        rval, rok = _resolve_device_target(aff.rtarget, group)
        total += abs(float(aff.weight))
        if check_affinity(
            aff.operand, lval, rval, lok, rok,
            regex_cache, version_cache,
        ):
            matched += float(aff.weight)
    return total, matched


class DeviceAllocator:
    def __init__(self, ctx, node: Node) -> None:
        self.ctx = ctx
        self.node = node
        self.accounter = DeviceAccounter(node)
        self._groups = {
            (g.vendor, g.type, g.name): g for g in node.node_resources.devices
        }

    def add_allocs(self, allocs: List[Allocation]) -> bool:
        return self.accounter.add_allocs(allocs)

    def add_reserved(self, offer: AllocatedDeviceResource) -> bool:
        return self.accounter.add_reserved(
            offer.vendor, offer.type, offer.name, offer.device_ids
        )

    def assign_device(
        self, ask: RequestedDevice
    ) -> Tuple[Optional[AllocatedDeviceResource], float, str]:
        """Pick the best feasible device group for the ask; returns
        (offer, sum_matched_affinity_weights, error)
        (reference device.go:32 AssignDevice)."""
        if not self._groups:
            return None, 0.0, "no devices available"
        if ask.count == 0:
            return None, 0.0, "invalid request of zero devices"

        offer: Optional[AllocatedDeviceResource] = None
        offer_score = 0.0
        matched_weights = 0.0

        for key, group in self._groups.items():
            free = self.accounter.free_instances(*key)
            if len(free) < ask.count:
                continue
            if not group.id().matches(ask.name):
                continue
            if not self._meets_constraints(group, ask):
                continue

            choice_score = 0.0
            sum_matched = 0.0
            if ask.affinities:
                total_weight, sum_matched = matched_affinity_weight(
                    group, ask.affinities,
                    self.ctx.regex_cache, self.ctx.version_cache,
                )
                choice_score = sum_matched
                if total_weight:
                    choice_score /= total_weight

            if offer is not None and choice_score < offer_score:
                continue

            offer_score = choice_score
            matched_weights = sum_matched
            offer = AllocatedDeviceResource(
                vendor=key[0],
                type=key[1],
                name=key[2],
                device_ids=free[: ask.count],
            )

        if offer is None:
            return None, 0.0, "no devices match request"
        return offer, matched_weights, ""

    def _meets_constraints(self, group, ask: RequestedDevice) -> bool:
        for constraint in ask.constraints:
            lval, lok = _resolve_device_target(constraint.ltarget, group)
            rval, rok = _resolve_device_target(constraint.rtarget, group)
            from .operators import check_constraint

            if not check_constraint(
                constraint.operand, lval, rval, lok, rok,
                self.ctx.regex_cache, self.ctx.version_cache,
            ):
                return False
        return True
