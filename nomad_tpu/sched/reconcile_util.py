"""Allocation set algebra for the reconciler
(reference scheduler/reconcile_util.go).
"""
from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..structs import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_DESIRED_EVICT,
    ALLOC_DESIRED_STOP,
    Allocation,
    Deployment,
    Job,
    Node,
    TaskGroup,
    alloc_name,
)

# AllocSet: dict alloc_id -> Allocation


@dataclass
class AllocStopResult:
    alloc: Allocation
    client_status: str = ""
    status_description: str = ""
    followup_eval_id: str = ""


@dataclass
class AllocPlaceResult:
    name: str = ""
    canary: bool = False
    task_group: Optional[TaskGroup] = None
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    downgrade_non_canary: bool = False
    min_job_version: int = 0

    def stop_previous_alloc(self) -> Tuple[bool, str]:
        return False, ""

    def is_rescheduling(self) -> bool:
        return self.reschedule


@dataclass
class AllocDestructiveResult:
    place_name: str = ""
    place_task_group: Optional[TaskGroup] = None
    stop_alloc: Optional[Allocation] = None
    stop_status_description: str = ""

    @property
    def name(self) -> str:
        return self.place_name

    @property
    def task_group(self) -> Optional[TaskGroup]:
        return self.place_task_group

    @property
    def previous_alloc(self) -> Optional[Allocation]:
        return self.stop_alloc

    @property
    def canary(self) -> bool:
        return False

    def stop_previous_alloc(self) -> Tuple[bool, str]:
        return True, self.stop_status_description

    def is_rescheduling(self) -> bool:
        return False


@dataclass
class DelayedRescheduleInfo:
    alloc_id: str
    alloc: Allocation
    reschedule_time: float


def new_alloc_matrix(
    job: Optional[Job], allocs: List[Allocation]
) -> Dict[str, Dict[str, Allocation]]:
    """Group -> {alloc id -> alloc}, in CANONICAL group order: the
    job's task_group order first, then orphaned groups sorted by name.
    The reference iterates this matrix in Go map order (random), which
    makes multi-group placement order — and, because the stack's walk
    offset persists across groups, placement OUTCOMES — nondeterministic
    across runs.  A deterministic order is required for this build's
    bit-identity contract (sequential vs batched paths, and test
    reproducibility across servers whose alloc ids differ)."""
    m: Dict[str, Dict[str, Allocation]] = {}
    if job is not None:
        for tg in job.task_groups:
            m.setdefault(tg.name, {})
    for alloc in sorted(allocs, key=lambda a: a.id):
        m.setdefault(alloc.task_group, {})[alloc.id] = alloc
    # orphaned groups (allocs of groups no longer in the job) were
    # appended in sorted-alloc order above; re-key them into name
    # order for full determinism
    if job is not None:
        job_names = [tg.name for tg in job.task_groups]
        orphans = sorted(
            name for name in m if name not in job_names
        )
        if orphans:
            m = {
                **{n: m[n] for n in job_names},
                **{n: m[n] for n in orphans},
            }
    return m


def name_order(allocs: Dict[str, Allocation]) -> List[Allocation]:
    return sorted(allocs.values(), key=lambda a: a.index())


def difference(
    a: Dict[str, Allocation], *others: Dict[str, Allocation]
) -> Dict[str, Allocation]:
    out = {}
    for k, v in a.items():
        if any(k in other for other in others):
            continue
        out[k] = v
    return out


def union(*sets: Dict[str, Allocation]) -> Dict[str, Allocation]:
    out: Dict[str, Allocation] = {}
    for s in sets:
        out.update(s)
    return out


def from_keys(
    a: Dict[str, Allocation], keys: List[str]
) -> Dict[str, Allocation]:
    return {k: a[k] for k in keys if k in a}


def filter_by_terminal(
    a: Dict[str, Allocation]
) -> Dict[str, Allocation]:
    return {k: v for k, v in a.items() if not v.terminal_status()}


def filter_by_tainted(
    a: Dict[str, Allocation], tainted: Dict[str, Optional[Node]]
) -> Tuple[
    Dict[str, Allocation], Dict[str, Allocation], Dict[str, Allocation]
]:
    """(untainted, migrate, lost)
    (reference reconcile_util.go:filterByTainted)."""
    untainted: Dict[str, Allocation] = {}
    migrate: Dict[str, Allocation] = {}
    lost: Dict[str, Allocation] = {}
    for alloc in a.values():
        if alloc.terminal_status():
            untainted[alloc.id] = alloc
            continue
        if alloc.desired_transition.should_migrate():
            migrate[alloc.id] = alloc
            continue
        if alloc.node_id not in tainted:
            untainted[alloc.id] = alloc
            continue
        node = tainted[alloc.node_id]
        if node is None or node.terminal_status():
            lost[alloc.id] = alloc
            continue
        untainted[alloc.id] = alloc
    return untainted, migrate, lost


def should_filter(alloc: Allocation, is_batch: bool) -> Tuple[bool, bool]:
    """(untainted, ignore) (reference reconcile_util.go:shouldFilter)."""
    if is_batch:
        if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            if alloc.ran_successfully():
                return True, False
            return False, True
        if alloc.client_status != ALLOC_CLIENT_STATUS_FAILED:
            return True, False
        return False, False

    if alloc.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
        return False, True
    if alloc.client_status in (
        ALLOC_CLIENT_STATUS_COMPLETE,
        ALLOC_CLIENT_STATUS_LOST,
    ):
        return False, True
    return False, False


RESCHEDULE_WINDOW_S = 1.0  # (reference reconcile.go:24)


def update_by_reschedulable(
    alloc: Allocation,
    now: float,
    eval_id: str,
    deployment: Optional[Deployment],
) -> Tuple[bool, bool, float]:
    """(reschedule_now, reschedule_later, reschedule_time)
    (reference reconcile_util.go:updateByReschedulable)."""
    if (
        deployment is not None
        and alloc.deployment_id == deployment.id
        and deployment.active()
        and not bool(alloc.desired_transition.reschedule)
    ):
        return False, False, 0.0

    reschedule_now = False
    if alloc.desired_transition.should_force_reschedule():
        reschedule_now = True

    reschedule_time, eligible = alloc.next_reschedule_time()
    if eligible and (
        alloc.followup_eval_id == eval_id
        or reschedule_time - now <= RESCHEDULE_WINDOW_S
    ):
        return True, False, reschedule_time
    if eligible and not alloc.followup_eval_id:
        return reschedule_now, True, reschedule_time
    return reschedule_now, False, reschedule_time


def filter_by_rescheduleable(
    a: Dict[str, Allocation],
    is_batch: bool,
    now: float,
    eval_id: str,
    deployment: Optional[Deployment],
) -> Tuple[
    Dict[str, Allocation],
    Dict[str, Allocation],
    List[DelayedRescheduleInfo],
]:
    """(untainted, reschedule_now, reschedule_later)."""
    untainted: Dict[str, Allocation] = {}
    reschedule_now: Dict[str, Allocation] = {}
    reschedule_later: List[DelayedRescheduleInfo] = []

    for alloc in a.values():
        if alloc.next_allocation and alloc.terminal_status():
            continue
        is_untainted, ignore = should_filter(alloc, is_batch)
        if is_untainted:
            untainted[alloc.id] = alloc
        if is_untainted or ignore:
            continue
        now_eligible, later_eligible, when = update_by_reschedulable(
            alloc, now, eval_id, deployment
        )
        if not now_eligible:
            untainted[alloc.id] = alloc
            if later_eligible:
                reschedule_later.append(
                    DelayedRescheduleInfo(alloc.id, alloc, when)
                )
        else:
            reschedule_now[alloc.id] = alloc
    return untainted, reschedule_now, reschedule_later


def filter_by_deployment(
    a: Dict[str, Allocation], deployment_id: str
) -> Tuple[Dict[str, Allocation], Dict[str, Allocation]]:
    match = {
        k: v for k, v in a.items() if v.deployment_id == deployment_id
    }
    nonmatch = {
        k: v for k, v in a.items() if v.deployment_id != deployment_id
    }
    return match, nonmatch


def delay_by_stop_after_client_disconnect(
    a: Dict[str, Allocation]
) -> List[DelayedRescheduleInfo]:
    now = _time.time()
    later = []
    for alloc in a.values():
        if not alloc.should_client_stop():
            continue
        t = alloc.wait_client_stop()
        if t > now:
            later.append(DelayedRescheduleInfo(alloc.id, alloc, t))
    return later


class AllocNameIndex:
    """Index-based alloc name chooser
    (reference reconcile_util.go:allocNameIndex, backed by a bitmap there;
    a Python set of used indexes has the same semantics)."""

    def __init__(
        self, job_id: str, task_group: str, count: int,
        existing: Dict[str, Allocation],
    ) -> None:
        self.job_id = job_id
        self.task_group = task_group
        self.count = count
        self.used: Set[int] = set()
        for alloc in existing.values():
            idx = alloc.index()
            if idx >= 0:
                self.used.add(idx)

    def _name(self, idx: int) -> str:
        return alloc_name(self.job_id, self.task_group, idx)

    def highest(self, n: int) -> Set[str]:
        out: Set[str] = set()
        for idx in sorted(self.used, reverse=True):
            if len(out) >= n:
                break
            self.used.discard(idx)
            out.add(self._name(idx))
        return out

    def unset_index(self, idx: int) -> None:
        self.used.discard(idx)

    def next(self, n: int) -> List[str]:
        out: List[str] = []
        for idx in range(self.count):
            if len(out) == n:
                return out
            if idx not in self.used:
                out.append(self._name(idx))
                self.used.add(idx)
        i = 0
        while len(out) < n:
            out.append(self._name(i))
            self.used.add(i)
            i += 1
        return out

    def next_canaries(
        self,
        n: int,
        existing: Dict[str, Allocation],
        destructive: Dict[str, Allocation],
    ) -> List[str]:
        next_names: List[str] = []
        existing_names = {a.name for a in existing.values()}

        destructive_idx = {
            a.index() for a in destructive.values() if a.index() >= 0
        }
        for idx in range(self.count):
            if idx in destructive_idx:
                name = self._name(idx)
                if name not in existing_names:
                    next_names.append(name)
                    self.used.add(idx)
                    if len(next_names) == n:
                        return next_names
        for idx in range(self.count):
            if idx not in self.used:
                name = self._name(idx)
                if name not in existing_names:
                    next_names.append(name)
                    self.used.add(idx)
                    if len(next_names) == n:
                        return next_names
        i = self.count
        while len(next_names) < n:
            next_names.append(self._name(i))
            i += 1
        return next_names
