"""The allocation reconciler: desired-vs-actual diff for service/batch jobs
(reference scheduler/reconcile.go).

Given the job spec, existing allocations, tainted nodes and the active
deployment, computes the sets of placements, stops, in-place updates,
destructive updates, deployment mutations and delayed follow-up evals.
"""
from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import (
    ALLOC_CLIENT_STATUS_LOST,
    Allocation,
    Deployment,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    DeploymentState,
    DeploymentStatusUpdate,
    DesiredUpdates,
    Evaluation,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    Job,
    Node,
    TaskGroup,
)
from .reconcile_util import (
    AllocDestructiveResult,
    AllocNameIndex,
    AllocPlaceResult,
    AllocStopResult,
    DelayedRescheduleInfo,
    delay_by_stop_after_client_disconnect,
    difference,
    filter_by_deployment,
    filter_by_rescheduleable,
    filter_by_tainted,
    filter_by_terminal,
    from_keys,
    name_order,
    new_alloc_matrix,
    union,
)

# status descriptions (reference scheduler/util.go + generic_sched.go)
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
BLOCKED_EVAL_MAX_PLAN_DESC = (
    "created due to placement conflicts"
)
BLOCKED_EVAL_FAILED_PLACEMENTS = (
    "created to place remaining allocations"
)
RESCHEDULING_FOLLOWUP_EVAL_DESC = (
    "created for delayed rescheduling"
)

BATCHED_FAILED_ALLOC_WINDOW_S = 5.0  # (reference reconcile.go:19)

# allocUpdateFn signature: (existing, new_job, new_tg) ->
#   (ignore, destructive, updated_alloc)
AllocUpdateFn = Callable[
    [Allocation, Job, TaskGroup],
    Tuple[bool, bool, Optional[Allocation]],
]


@dataclass
class ReconcileResults:
    """(reference reconcile.go:90 reconcileResults)"""

    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(
        default_factory=list
    )
    place: List[AllocPlaceResult] = field(default_factory=list)
    destructive_update: List[AllocDestructiveResult] = field(
        default_factory=list
    )
    inplace_update: List[Allocation] = field(default_factory=list)
    stop: List[AllocStopResult] = field(default_factory=list)
    attribute_updates: Dict[str, Allocation] = field(default_factory=dict)
    desired_tg_updates: Dict[str, DesiredUpdates] = field(
        default_factory=dict
    )
    desired_followup_evals: Dict[str, List[Evaluation]] = field(
        default_factory=dict
    )

    def changes(self) -> int:
        return len(self.place) + len(self.inplace_update) + len(self.stop)


class AllocReconciler:
    def __init__(
        self,
        alloc_update_fn: AllocUpdateFn,
        batch: bool,
        job_id: str,
        job: Optional[Job],
        deployment: Optional[Deployment],
        existing_allocs: List[Allocation],
        tainted_nodes: Dict[str, Optional[Node]],
        eval_id: str,
        now: Optional[float] = None,
    ) -> None:
        self.alloc_update_fn = alloc_update_fn
        self.batch = batch
        self.job_id = job_id
        self.job = job
        self.deployment = deployment
        self.old_deployment: Optional[Deployment] = None
        self.deployment_paused = False
        self.deployment_failed = False
        self.tainted_nodes = tainted_nodes
        self.existing_allocs = existing_allocs
        self.eval_id = eval_id
        self.now = now if now is not None else _time.time()
        self.result = ReconcileResults()

    # ------------------------------------------------------------------

    def compute(self) -> ReconcileResults:
        m = new_alloc_matrix(self.job, self.existing_allocs)
        self._cancel_deployments()

        if self.job is None or self.job.stopped():
            self._handle_stop(m)
            return self.result

        if self.deployment is not None:
            self.deployment_paused = (
                self.deployment.status == DEPLOYMENT_STATUS_PAUSED
            )
            self.deployment_failed = (
                self.deployment.status == DEPLOYMENT_STATUS_FAILED
            )

        complete = True
        for group, allocs in m.items():
            group_complete = self._compute_group(group, allocs)
            complete = complete and group_complete

        if self.deployment is not None and complete:
            self.result.deployment_updates.append(
                DeploymentStatusUpdate(
                    deployment_id=self.deployment.id,
                    status=DEPLOYMENT_STATUS_SUCCESSFUL,
                    status_description="Deployment completed successfully",
                )
            )

        d = self.result.deployment
        if d is not None and d.requires_promotion():
            if d.has_auto_promote():
                d.status_description = (
                    "Deployment is running pending automatic promotion"
                )
            else:
                d.status_description = (
                    "Deployment is running but requires promotion"
                )
        return self.result

    # ------------------------------------------------------------------

    def _cancel_deployments(self) -> None:
        if self.job is None or self.job.stopped():
            if self.deployment is not None and self.deployment.active():
                self.result.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=self.deployment.id,
                        status=DEPLOYMENT_STATUS_CANCELLED,
                        status_description=(
                            "Cancelled because job is stopped"
                        ),
                    )
                )
            self.old_deployment = self.deployment
            self.deployment = None
            return

        d = self.deployment
        if d is None:
            return
        if (
            d.job_create_index != self.job.create_index
            or d.job_version != self.job.version
        ):
            if d.active():
                self.result.deployment_updates.append(
                    DeploymentStatusUpdate(
                        deployment_id=d.id,
                        status=DEPLOYMENT_STATUS_CANCELLED,
                        status_description=(
                            "Cancelled due to newer version of job"
                        ),
                    )
                )
            self.old_deployment = d
            self.deployment = None
        elif d.status == DEPLOYMENT_STATUS_SUCCESSFUL:
            self.old_deployment = d
            self.deployment = None

    def _handle_stop(self, m: Dict[str, Dict[str, Allocation]]) -> None:
        for group, allocs in m.items():
            allocs = filter_by_terminal(allocs)
            untainted, migrate, lost = filter_by_tainted(
                allocs, self.tainted_nodes
            )
            self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_STATUS_LOST, ALLOC_LOST)
            desired = DesiredUpdates(stop=len(allocs))
            self.result.desired_tg_updates[group] = desired

    def _mark_stop(
        self,
        allocs: Dict[str, Allocation],
        client_status: str,
        description: str,
    ) -> None:
        for alloc in allocs.values():
            self.result.stop.append(
                AllocStopResult(
                    alloc=alloc,
                    client_status=client_status,
                    status_description=description,
                )
            )

    def _mark_delayed(
        self,
        allocs: Dict[str, Allocation],
        client_status: str,
        description: str,
        followup_evals: Dict[str, str],
    ) -> None:
        for alloc in allocs.values():
            self.result.stop.append(
                AllocStopResult(
                    alloc=alloc,
                    client_status=client_status,
                    status_description=description,
                    followup_eval_id=followup_evals.get(alloc.id, ""),
                )
            )

    # ------------------------------------------------------------------

    def _compute_group(
        self, group: str, all_allocs: Dict[str, Allocation]
    ) -> bool:
        desired = DesiredUpdates()
        self.result.desired_tg_updates[group] = desired

        tg = self.job.lookup_task_group(group)
        if tg is None:
            untainted, migrate, lost = filter_by_tainted(
                all_allocs, self.tainted_nodes
            )
            self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_STATUS_LOST, ALLOC_LOST)
            desired.stop = len(untainted) + len(migrate) + len(lost)
            return True

        dstate: Optional[DeploymentState] = None
        existing_deployment = False
        if self.deployment is not None:
            dstate = self.deployment.task_groups.get(group)
            existing_deployment = dstate is not None
        if not existing_deployment:
            dstate = DeploymentState()
            if tg.update is not None and not tg.update.is_empty():
                dstate.auto_revert = tg.update.auto_revert
                dstate.auto_promote = tg.update.auto_promote
                dstate.progress_deadline_s = tg.update.progress_deadline_s

        all_allocs, ignore = self._filter_old_terminal_allocs(all_allocs)
        desired.ignore += len(ignore)

        canaries, all_allocs = self._handle_group_canaries(
            all_allocs, desired
        )

        untainted, migrate, lost = filter_by_tainted(
            all_allocs, self.tainted_nodes
        )

        untainted, reschedule_now, reschedule_later = (
            filter_by_rescheduleable(
                untainted, self.batch, self.now, self.eval_id,
                self.deployment,
            )
        )

        lost_later = delay_by_stop_after_client_disconnect(lost)
        lost_later_evals = self._handle_delayed_lost(
            lost_later, all_allocs, tg.name
        )

        self._handle_delayed_reschedules(
            reschedule_later, all_allocs, tg.name
        )

        name_index = AllocNameIndex(
            self.job_id, group, tg.count,
            union(untainted, migrate, reschedule_now),
        )

        canary_state = (
            dstate is not None
            and dstate.desired_canaries != 0
            and not dstate.promoted
        )
        stop = self._compute_stop(
            tg, name_index, untainted, migrate, lost, canaries,
            canary_state, lost_later_evals,
        )
        desired.stop += len(stop)
        untainted = difference(untainted, stop)

        ignore_set, inplace, destructive = self._compute_updates(
            tg, untainted
        )
        desired.ignore += len(ignore_set)
        desired.in_place_update += len(inplace)
        if not existing_deployment:
            dstate.desired_total += len(destructive) + len(inplace)

        if canary_state:
            untainted = difference(untainted, canaries)

        strategy = tg.update
        canaries_promoted = dstate is not None and dstate.promoted
        require_canary = (
            len(destructive) != 0
            and strategy is not None
            and len(canaries) < strategy.canary
            and not canaries_promoted
        )
        if require_canary:
            dstate.desired_canaries = strategy.canary
        if (
            require_canary
            and not self.deployment_paused
            and not self.deployment_failed
        ):
            number = strategy.canary - len(canaries)
            desired.canary += number
            for name in name_index.next_canaries(
                number, canaries, destructive
            ):
                self.result.place.append(
                    AllocPlaceResult(
                        name=name, canary=True, task_group=tg
                    )
                )

        canary_state = (
            dstate is not None
            and dstate.desired_canaries != 0
            and not dstate.promoted
        )
        limit = self._compute_limit(
            tg, untainted, destructive, migrate, canary_state
        )

        place: List[AllocPlaceResult] = []
        if not lost_later:
            place = self._compute_placements(
                tg, name_index, untainted, migrate, reschedule_now,
                canary_state,
            )
            if not existing_deployment:
                dstate.desired_total += len(place)

        deployment_place_ready = (
            not self.deployment_paused
            and not self.deployment_failed
            and not canary_state
        )

        if deployment_place_ready:
            desired.place += len(place)
            self.result.place.extend(place)
            self._mark_stop(reschedule_now, "", ALLOC_RESCHEDULED)
            desired.stop += len(reschedule_now)
            limit -= min(len(place), limit)
        else:
            if lost:
                allowed = min(len(lost), len(place))
                desired.place += allowed
                self.result.place.extend(place[:allowed])
            if reschedule_now:
                for p in place:
                    prev = p.previous_alloc
                    if p.is_rescheduling() and not (
                        self.deployment_failed
                        and prev is not None
                        and self.deployment is not None
                        and self.deployment.id == prev.deployment_id
                    ):
                        self.result.place.append(p)
                        desired.place += 1
                        self.result.stop.append(
                            AllocStopResult(
                                alloc=prev,
                                status_description=ALLOC_RESCHEDULED,
                            )
                        )
                        desired.stop += 1

        if deployment_place_ready:
            n = min(len(destructive), limit)
            desired.destructive_update += n
            desired.ignore += len(destructive) - n
            for alloc in name_order(destructive)[:n]:
                self.result.destructive_update.append(
                    AllocDestructiveResult(
                        place_name=alloc.name,
                        place_task_group=tg,
                        stop_alloc=alloc,
                        stop_status_description=ALLOC_UPDATING,
                    )
                )
        else:
            desired.ignore += len(destructive)

        desired.migrate += len(migrate)
        for alloc in name_order(migrate):
            is_canary = (
                alloc.deployment_status is not None
                and alloc.deployment_status.canary
            )
            self.result.stop.append(
                AllocStopResult(
                    alloc=alloc, status_description=ALLOC_MIGRATING
                )
            )
            self.result.place.append(
                AllocPlaceResult(
                    name=alloc.name,
                    canary=is_canary,
                    task_group=tg,
                    previous_alloc=alloc,
                    downgrade_non_canary=canary_state and not is_canary,
                    min_job_version=(
                        alloc.job.version if alloc.job else 0
                    ),
                )
            )

        # deployment creation (reference reconcile.go:545)
        updating_spec = bool(destructive) or bool(
            self.result.inplace_update
        )
        had_running = any(
            alloc.job is not None
            and alloc.job.version == self.job.version
            and alloc.job.create_index == self.job.create_index
            for alloc in all_allocs.values()
        )
        if (
            not existing_deployment
            and strategy is not None
            and not strategy.is_empty()
            and dstate.desired_total != 0
            and (not had_running or updating_spec)
        ):
            if self.deployment is None:
                self.deployment = Deployment(
                    namespace=self.job.namespace,
                    job_id=self.job.id,
                    job_version=self.job.version,
                    job_modify_index=self.job.modify_index,
                    job_create_index=self.job.create_index,
                    status=DEPLOYMENT_STATUS_RUNNING,
                )
                self.result.deployment = self.deployment
            self.deployment.task_groups[group] = dstate

        deployment_complete = (
            len(destructive)
            + len(inplace)
            + len(place)
            + len(migrate)
            + len(reschedule_now)
            + len(reschedule_later)
            == 0
            and not require_canary
        )
        if deployment_complete and self.deployment is not None:
            ds = self.deployment.task_groups.get(group)
            if ds is not None:
                if ds.healthy_allocs < max(
                    ds.desired_total, ds.desired_canaries
                ) or (ds.desired_canaries > 0 and not ds.promoted):
                    deployment_complete = False
        return deployment_complete

    # ------------------------------------------------------------------

    def _filter_old_terminal_allocs(
        self, all_allocs: Dict[str, Allocation]
    ) -> Tuple[Dict[str, Allocation], Dict[str, Allocation]]:
        if not self.batch:
            return all_allocs, {}
        filtered = dict(all_allocs)
        ignored: Dict[str, Allocation] = {}
        for aid, alloc in list(filtered.items()):
            older = alloc.job is not None and (
                alloc.job.version < self.job.version
                or alloc.job.create_index < self.job.create_index
            )
            if older and alloc.terminal_status():
                del filtered[aid]
                ignored[aid] = alloc
        return filtered, ignored

    def _handle_group_canaries(
        self,
        all_allocs: Dict[str, Allocation],
        desired: DesiredUpdates,
    ) -> Tuple[Dict[str, Allocation], Dict[str, Allocation]]:
        stop_ids: List[str] = []
        if self.old_deployment is not None:
            for ds in self.old_deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)
        if (
            self.deployment is not None
            and self.deployment.status == DEPLOYMENT_STATUS_FAILED
        ):
            for ds in self.deployment.task_groups.values():
                if not ds.promoted:
                    stop_ids.extend(ds.placed_canaries)

        stop_set = from_keys(all_allocs, stop_ids)
        self._mark_stop(stop_set, "", ALLOC_NOT_NEEDED)
        desired.stop += len(stop_set)
        all_allocs = difference(all_allocs, stop_set)

        canaries: Dict[str, Allocation] = {}
        if self.deployment is not None:
            canary_ids: List[str] = []
            for ds in self.deployment.task_groups.values():
                canary_ids.extend(ds.placed_canaries)
            canaries = from_keys(all_allocs, canary_ids)
            untainted, migrate, lost = filter_by_tainted(
                canaries, self.tainted_nodes
            )
            self._mark_stop(migrate, "", ALLOC_MIGRATING)
            self._mark_stop(lost, ALLOC_CLIENT_STATUS_LOST, ALLOC_LOST)
            canaries = untainted
            all_allocs = difference(all_allocs, migrate, lost)
        return canaries, all_allocs

    def _compute_limit(
        self,
        tg: TaskGroup,
        untainted: Dict[str, Allocation],
        destructive: Dict[str, Allocation],
        migrate: Dict[str, Allocation],
        canary_state: bool,
    ) -> int:
        """(reference reconcile.go:668 computeLimit)"""
        if (
            tg.update is None
            or tg.update.is_empty()
            or len(destructive) + len(migrate) == 0
        ):
            return tg.count
        if self.deployment_paused or self.deployment_failed:
            return 0
        if canary_state:
            return 0
        limit = tg.update.max_parallel
        if self.deployment is not None:
            part_of, _ = filter_by_deployment(
                untainted, self.deployment.id
            )
            for alloc in part_of.values():
                if (
                    alloc.deployment_status is not None
                    and alloc.deployment_status.is_unhealthy()
                ):
                    return 0
                if (
                    alloc.deployment_status is None
                    or not alloc.deployment_status.is_healthy()
                ):
                    limit -= 1
        return max(0, limit)

    def _compute_placements(
        self,
        tg: TaskGroup,
        name_index: AllocNameIndex,
        untainted: Dict[str, Allocation],
        migrate: Dict[str, Allocation],
        reschedule: Dict[str, Allocation],
        canary_state: bool,
    ) -> List[AllocPlaceResult]:
        place: List[AllocPlaceResult] = []
        for alloc in reschedule.values():
            is_canary = (
                alloc.deployment_status is not None
                and alloc.deployment_status.canary
            )
            place.append(
                AllocPlaceResult(
                    name=alloc.name,
                    task_group=tg,
                    previous_alloc=alloc,
                    reschedule=True,
                    canary=is_canary,
                    downgrade_non_canary=canary_state and not is_canary,
                    min_job_version=(
                        alloc.job.version if alloc.job else 0
                    ),
                )
            )
        existing = len(untainted) + len(migrate) + len(reschedule)
        if existing < tg.count:
            for name in name_index.next(tg.count - existing):
                place.append(
                    AllocPlaceResult(
                        name=name,
                        task_group=tg,
                        downgrade_non_canary=canary_state,
                    )
                )
        return place

    def _compute_stop(
        self,
        tg: TaskGroup,
        name_index: AllocNameIndex,
        untainted: Dict[str, Allocation],
        migrate: Dict[str, Allocation],
        lost: Dict[str, Allocation],
        canaries: Dict[str, Allocation],
        canary_state: bool,
        followup_evals: Dict[str, str],
    ) -> Dict[str, Allocation]:
        stop: Dict[str, Allocation] = dict(lost)
        self._mark_delayed(
            lost, ALLOC_CLIENT_STATUS_LOST, ALLOC_LOST, followup_evals
        )

        if canary_state:
            untainted = difference(untainted, canaries)

        remove = len(untainted) + len(migrate) - tg.count
        if remove <= 0:
            return stop

        untainted = filter_by_terminal(untainted)

        if not canary_state and canaries:
            canary_names = {a.name for a in canaries.values()}
            for aid, alloc in list(
                difference(untainted, canaries).items()
            ):
                if alloc.name in canary_names:
                    stop[aid] = alloc
                    self.result.stop.append(
                        AllocStopResult(
                            alloc=alloc,
                            status_description=ALLOC_NOT_NEEDED,
                        )
                    )
                    del untainted[aid]
                    remove -= 1
                    if remove == 0:
                        return stop

        if migrate:
            migrate_index = AllocNameIndex(
                self.job_id, tg.name, tg.count, migrate
            )
            remove_names = migrate_index.highest(remove)
            for aid, alloc in list(migrate.items()):
                if alloc.name not in remove_names:
                    continue
                self.result.stop.append(
                    AllocStopResult(
                        alloc=alloc,
                        status_description=ALLOC_NOT_NEEDED,
                    )
                )
                del migrate[aid]
                stop[aid] = alloc
                name_index.unset_index(alloc.index())
                remove -= 1
                if remove == 0:
                    return stop

        remove_names = name_index.highest(remove)
        for aid, alloc in list(untainted.items()):
            if alloc.name in remove_names:
                stop[aid] = alloc
                self.result.stop.append(
                    AllocStopResult(
                        alloc=alloc,
                        status_description=ALLOC_NOT_NEEDED,
                    )
                )
                del untainted[aid]
                remove -= 1
                if remove == 0:
                    return stop

        for aid, alloc in list(untainted.items()):
            stop[aid] = alloc
            self.result.stop.append(
                AllocStopResult(
                    alloc=alloc, status_description=ALLOC_NOT_NEEDED
                )
            )
            del untainted[aid]
            remove -= 1
            if remove == 0:
                return stop
        return stop

    def _compute_updates(
        self, tg: TaskGroup, untainted: Dict[str, Allocation]
    ) -> Tuple[
        Dict[str, Allocation],
        Dict[str, Allocation],
        Dict[str, Allocation],
    ]:
        ignore: Dict[str, Allocation] = {}
        inplace: Dict[str, Allocation] = {}
        destructive: Dict[str, Allocation] = {}
        for alloc in untainted.values():
            ignore_change, destructive_change, updated = (
                self.alloc_update_fn(alloc, self.job, tg)
            )
            if ignore_change:
                ignore[alloc.id] = alloc
            elif destructive_change:
                destructive[alloc.id] = alloc
            else:
                inplace[alloc.id] = alloc
                if updated is not None:
                    self.result.inplace_update.append(updated)
        return ignore, inplace, destructive

    # ------------------------------------------------------------------

    def _handle_delayed_reschedules(
        self,
        reschedule_later: List[DelayedRescheduleInfo],
        all_allocs: Dict[str, Allocation],
        tg_name: str,
    ) -> None:
        mapping = self._handle_delayed_lost(
            reschedule_later, all_allocs, tg_name
        )
        for alloc_id, eval_id in mapping.items():
            existing = all_allocs.get(alloc_id)
            if existing is None:
                continue
            from dataclasses import replace as _replace

            updated = _replace(existing)
            updated.followup_eval_id = eval_id
            self.result.attribute_updates[updated.id] = updated

    def _handle_delayed_lost(
        self,
        reschedule_later: List[DelayedRescheduleInfo],
        all_allocs: Dict[str, Allocation],
        tg_name: str,
    ) -> Dict[str, str]:
        """Batch delayed reschedules into follow-up evals within a 5s
        window (reference reconcile.go:869 handleDelayedLost)."""
        if not reschedule_later:
            return {}
        reschedule_later = sorted(
            reschedule_later, key=lambda i: i.reschedule_time
        )
        evals: List[Evaluation] = []
        next_time = reschedule_later[0].reschedule_time
        mapping: Dict[str, str] = {}
        ev = Evaluation(
            namespace=self.job.namespace,
            priority=self.job.priority,
            type=self.job.type,
            triggered_by=EVAL_TRIGGER_RETRY_FAILED_ALLOC,
            job_id=self.job.id,
            job_modify_index=self.job.modify_index,
            status=EVAL_STATUS_PENDING,
            status_description=RESCHEDULING_FOLLOWUP_EVAL_DESC,
            wait_until=next_time,
        )
        evals.append(ev)
        for info in reschedule_later:
            if info.reschedule_time - next_time < (
                BATCHED_FAILED_ALLOC_WINDOW_S
            ):
                mapping[info.alloc_id] = ev.id
            else:
                next_time = info.reschedule_time
                ev = Evaluation(
                    namespace=self.job.namespace,
                    priority=self.job.priority,
                    type=self.job.type,
                    triggered_by=EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                    job_id=self.job.id,
                    job_modify_index=self.job.modify_index,
                    status=EVAL_STATUS_PENDING,
                    wait_until=next_time,
                )
                evals.append(ev)
                mapping[info.alloc_id] = ev.id
        self.result.desired_followup_evals[tg_name] = evals
        return mapping
