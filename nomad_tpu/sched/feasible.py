"""Feasibility checking: the host-side oracle chain.

A faithful re-expression of the reference's `scheduler/feasible.go`:
pull-based FeasibleIterators and FeasibilityCheckers, including the
computed-class memoization wrapper (feasible.go:994) that lets repeated
checks on identical node classes short-circuit.  The vectorized mask
equivalents live in `nomad_tpu/ops/constraints.py`.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from ..structs import (
    Constraint,
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    Job,
    NetworkIndex,
    Node,
    TaskGroup,
    VolumeRequest,
)
from ..structs.device_accounting import DeviceAccounter
from .context import (
    CLASS_ELIGIBLE,
    CLASS_ESCAPED,
    CLASS_INELIGIBLE,
    CLASS_UNKNOWN,
    EvalContext,
)
from .operators import check_constraint
from .propertyset import PropertySet

FILTER_CONSTRAINT_DRIVERS = "missing drivers"
FILTER_CONSTRAINT_DEVICES = "missing devices"
FILTER_CONSTRAINT_HOST_VOLUMES = "missing compatible host volumes"
FILTER_CONSTRAINT_CSI_VOLUMES = "missing CSI plugins"
FILTER_CONSTRAINT_NETWORK = "missing network"
# the memoized-class short-circuit reason (FeasibilityWrapper)
FILTER_CLASS_INELIGIBLE = "computed class ineligible"


def resolve_target(target: str, node: Node) -> Tuple[Optional[str], bool]:
    """Interpolate a constraint target against a node
    (reference feasible.go:713 resolveTarget)."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        key = target[len("${attr.") : -1]
        val = node.attributes.get(key)
        return val, val is not None
    if target.startswith("${meta."):
        key = target[len("${meta.") : -1]
        val = node.meta.get(key)
        return val, val is not None
    return None, False


def target_column_key(target: str) -> Optional[str]:
    """Map a constraint target to a NodeTable column key; None for literal
    values, "" for unresolvable interpolations."""
    if not target.startswith("${"):
        return None
    if target == "${node.unique.id}":
        return "node.id"
    if target == "${node.datacenter}":
        return "node.datacenter"
    if target == "${node.unique.name}":
        return "node.name"
    if target == "${node.class}":
        return "node.class"
    if target.startswith("${attr."):
        return "attr." + target[len("${attr.") : -1]
    if target.startswith("${meta."):
        return "meta." + target[len("${meta.") : -1]
    return ""


# ---------------------------------------------------------------------------
# Source iterators
# ---------------------------------------------------------------------------


class StaticIterator:
    """Returns nodes in a fixed order (reference feasible.go:75); the
    "random" variant is the same iterator over a pre-shuffled list."""

    def __init__(self, ctx: EvalContext, nodes: List[Node]) -> None:
        self.ctx = ctx
        self.nodes = nodes
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[Node]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        self.ctx.metrics.evaluate_node()
        return option

    def reset(self) -> None:
        self.seen = 0

    def set_nodes(self, nodes: List[Node]) -> None:
        self.nodes = nodes
        self.offset = 0
        self.seen = 0


def new_random_iterator(ctx: EvalContext, nodes: List[Node]) -> StaticIterator:
    nodes = list(nodes)
    shuffle_nodes(ctx.rng, nodes)
    return StaticIterator(ctx, nodes)


def shuffle_nodes(rng, nodes: List[Node]) -> None:
    """Seeded shuffle (reference scheduler/util.go:338 shuffleNodes uses
    Fisher-Yates over the global rand).  Implemented as a numpy
    permutation keyed off the context RNG so (a) the oracle and the TPU
    kernel path derive the *identical* visit order from the same seed and
    (b) shuffling 10k+ nodes costs microseconds, not milliseconds."""
    order = shuffle_permutation(rng, len(nodes))
    nodes[:] = [nodes[i] for i in order]


def shuffle_permutation(rng, n: int) -> "np.ndarray":
    """The permutation `shuffle_nodes` applies, as indices."""
    import numpy as np

    seed = rng.randrange(2**32)
    return np.random.default_rng(seed).permutation(n)


# ---------------------------------------------------------------------------
# Checkers
# ---------------------------------------------------------------------------


class ConstraintChecker:
    """(reference feasible.go:674)"""

    def __init__(self, ctx: EvalContext, constraints: List[Constraint]) -> None:
        self.ctx = ctx
        self.constraints = constraints

    def set_constraints(self, constraints: List[Constraint]) -> None:
        self.constraints = constraints

    def feasible(self, option: Node) -> bool:
        for constraint in self.constraints:
            if not self._meets(constraint, option):
                self.ctx.metrics.filter_node(option, str(constraint))
                return False
        return True

    def _meets(self, constraint: Constraint, option: Node) -> bool:
        lval, lok = resolve_target(constraint.ltarget, option)
        rval, rok = resolve_target(constraint.rtarget, option)
        return check_constraint(
            constraint.operand,
            lval,
            rval,
            lok,
            rok,
            self.ctx.regex_cache,
            self.ctx.version_cache,
        )


class DriverChecker:
    """(reference feasible.go:398)"""

    def __init__(self, ctx: EvalContext, drivers: Iterable[str] = ()) -> None:
        self.ctx = ctx
        self.drivers = set(drivers)

    def set_drivers(self, drivers: Iterable[str]) -> None:
        self.drivers = set(drivers)

    def feasible(self, option: Node) -> bool:
        if self._has_drivers(option):
            return True
        self.ctx.metrics.filter_node(option, FILTER_CONSTRAINT_DRIVERS)
        return False

    def _has_drivers(self, option: Node) -> bool:
        for driver in self.drivers:
            if driver in option.drivers:
                if not option.drivers[driver]:
                    return False
                continue
            value = option.attributes.get(f"driver.{driver}")
            if value is None or value in ("", "0", "false", "False"):
                return False
        return True


class HostVolumeChecker:
    """(reference feasible.go:117)"""

    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.volumes: Dict[str, VolumeRequest] = {}

    def set_volumes(self, volumes: Dict[str, VolumeRequest]) -> None:
        self.volumes = {
            name: req for name, req in volumes.items() if req.type == "host"
        }

    def feasible(self, option: Node) -> bool:
        for req in self.volumes.values():
            vol = option.host_volumes.get(req.source)
            if vol is None:
                self.ctx.metrics.filter_node(
                    option, FILTER_CONSTRAINT_HOST_VOLUMES
                )
                return False
            if vol.read_only and not req.read_only:
                self.ctx.metrics.filter_node(
                    option, FILTER_CONSTRAINT_HOST_VOLUMES
                )
                return False
        return True


class CSIVolumeChecker:
    """CSI feasibility (reference feasible.go:194 CSIVolumeChecker):
    each requested volume must be registered, schedulable, have claim
    capacity for the requested access, and the node must run a healthy
    instance of the plugin backing it."""

    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.namespace = "default"
        self.requests: List[VolumeRequest] = []

    def set_namespace(self, namespace: str) -> None:
        self.namespace = namespace

    def set_volumes(self, volumes: Dict[str, VolumeRequest]) -> None:
        self.requests = [
            req for req in volumes.values() if req.type == "csi"
        ]

    def feasible(self, option: Node) -> bool:
        for req in self.requests:
            vol = self.ctx.state.csi_volume_by_id(
                self.namespace, req.source
            )
            if (
                vol is None
                or not vol.claimable(req.read_only)
                or not option.csi_node_plugins.get(vol.plugin_id, False)
            ):
                self.ctx.metrics.filter_node(
                    option, FILTER_CONSTRAINT_CSI_VOLUMES
                )
                return False
        return True


class NetworkChecker:
    """(reference feasible.go:319)"""

    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.network_mode = "host"

    def set_network(self, network) -> None:
        self.network_mode = network.mode or "host"

    def feasible(self, option: Node) -> bool:
        if self.network_mode in ("host", ""):
            return True
        for net in option.node_resources.networks:
            if (net.mode or "host") == self.network_mode:
                return True
        self.ctx.metrics.filter_node(option, FILTER_CONSTRAINT_NETWORK)
        return False


class DeviceChecker:
    """Whether a node can possibly satisfy the task group's device asks,
    counting instances and applying device-attribute constraints
    (reference feasible.go:1138)."""

    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.required: List = []

    def set_task_group(self, tg: TaskGroup) -> None:
        self.required = [
            req
            for task in tg.tasks
            for req in task.resources.devices
        ]

    def feasible(self, option: Node) -> bool:
        if not self.required:
            return True
        if self._has_devices(option):
            return True
        self.ctx.metrics.filter_node(option, FILTER_CONSTRAINT_DEVICES)
        return False

    def _has_devices(self, option: Node) -> bool:
        for req in self.required:
            available = 0
            for group in option.node_resources.devices:
                if not group.id().matches(req.name):
                    continue
                if not self._group_meets_constraints(group, req):
                    continue
                available += len(group.instance_ids)
            if available < req.count:
                return False
        return True

    def _group_meets_constraints(self, group, req) -> bool:
        for constraint in req.constraints:
            lval, lok = _resolve_device_target(
                constraint.ltarget, group
            )
            rval, rok = _resolve_device_target(constraint.rtarget, group)
            if not check_constraint(
                constraint.operand,
                lval,
                rval,
                lok,
                rok,
                self.ctx.regex_cache,
                self.ctx.version_cache,
            ):
                return False
        return True


def _resolve_device_target(target: str, group) -> Tuple[Optional[str], bool]:
    if not target.startswith("${"):
        return target, True
    if target.startswith("${device.attr."):
        key = target[len("${device.attr.") : -1]
        val = group.attributes.get(key)
        return (str(val), True) if val is not None else (None, False)
    if target == "${device.model}":
        return group.name, True
    if target == "${device.vendor}":
        return group.vendor, True
    if target == "${device.type}":
        return group.type, True
    return None, False


# ---------------------------------------------------------------------------
# Distinct hosts / distinct property iterators
# ---------------------------------------------------------------------------


class DistinctHostsIterator:
    """(reference feasible.go:470)"""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.job: Optional[Job] = None
        self.tg: Optional[TaskGroup] = None
        self.job_distinct = False
        self.tg_distinct = False

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS for c in job.constraints
        )

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        self.tg_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS for c in tg.constraints
        )

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None or not (self.job_distinct or self.tg_distinct):
                return option
            if not self._satisfies(option):
                self.ctx.metrics.filter_node(option, CONSTRAINT_DISTINCT_HOSTS)
                continue
            return option

    def _satisfies(self, option: Node) -> bool:
        proposed = self.ctx.proposed_allocs(option.id)
        for alloc in proposed:
            job_collision = alloc.job_id == self.job.id
            task_collision = alloc.task_group == self.tg.name
            if (self.job_distinct and job_collision) or (
                job_collision and task_collision
            ):
                return False
        return True

    def reset(self) -> None:
        self.source.reset()


class DistinctPropertyIterator:
    """(reference feasible.go:569)"""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.job: Optional[Job] = None
        self.tg: Optional[TaskGroup] = None
        self.job_property_sets: List[PropertySet] = []
        self.group_property_sets: Dict[str, List[PropertySet]] = {}
        self.has_constraints = False

    def set_job(self, job: Job) -> None:
        self.job = job
        for c in job.constraints:
            if c.operand != CONSTRAINT_DISTINCT_PROPERTY:
                continue
            pset = PropertySet(self.ctx, job)
            pset.set_constraint(c, "")
            self.job_property_sets.append(pset)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        if tg.name not in self.group_property_sets:
            sets = []
            for c in tg.constraints:
                if c.operand != CONSTRAINT_DISTINCT_PROPERTY:
                    continue
                pset = PropertySet(self.ctx, self.job)
                pset.set_constraint(c, tg.name)
                sets.append(pset)
            self.group_property_sets[tg.name] = sets
        self.has_constraints = bool(
            self.job_property_sets or self.group_property_sets[tg.name]
        )

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None or not self.has_constraints:
                return option
            if not self._satisfies(option, self.job_property_sets):
                continue
            if not self._satisfies(
                option, self.group_property_sets.get(self.tg.name, [])
            ):
                continue
            return option

    def _satisfies(self, option: Node, sets: List[PropertySet]) -> bool:
        for ps in sets:
            ok, reason = ps.satisfies_distinct_properties(option, self.tg.name)
            if not ok:
                self.ctx.metrics.filter_node(option, reason)
                return False
        return True

    def reset(self) -> None:
        self.source.reset()
        for ps in self.job_property_sets:
            ps.populate_proposed()
        for sets in self.group_property_sets.values():
            for ps in sets:
                ps.populate_proposed()


# ---------------------------------------------------------------------------
# Feasibility wrapper with computed-class memoization
# ---------------------------------------------------------------------------


class FeasibilityWrapper:
    """(reference feasible.go:994; Next at :1026)"""

    def __init__(
        self,
        ctx: EvalContext,
        source,
        job_checkers: List,
        tg_checkers: List,
        tg_available: List,
    ) -> None:
        self.ctx = ctx
        self.source = source
        self.job_checkers = job_checkers
        self.tg_checkers = tg_checkers
        self.tg_available = tg_available
        self.tg = ""

    def set_task_group(self, tg: str) -> None:
        self.tg = tg

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[Node]:
        elig = self.ctx.eligibility
        metrics = self.ctx.metrics
        while True:
            option = self.source.next()
            if option is None:
                return None

            job_escaped = job_unknown = False
            status = elig.job_status(option.computed_class)
            if status == CLASS_INELIGIBLE:
                metrics.filter_node(option, FILTER_CLASS_INELIGIBLE)
                continue
            elif status == CLASS_ESCAPED:
                job_escaped = True
            elif status == CLASS_UNKNOWN:
                job_unknown = True

            failed_job = False
            for check in self.job_checkers:
                if not check.feasible(option):
                    if not job_escaped:
                        elig.set_job_eligibility(False, option.computed_class)
                    failed_job = True
                    break
            if failed_job:
                continue

            if not job_escaped and job_unknown:
                elig.set_job_eligibility(True, option.computed_class)

            tg_escaped = tg_unknown = False
            status = elig.task_group_status(self.tg, option.computed_class)
            if status == CLASS_INELIGIBLE:
                metrics.filter_node(option, FILTER_CLASS_INELIGIBLE)
                continue
            elif status == CLASS_ELIGIBLE:
                if self._available(option):
                    return option
                # class matches but transiently unavailable: block
                return None
            elif status == CLASS_ESCAPED:
                tg_escaped = True
            elif status == CLASS_UNKNOWN:
                tg_unknown = True

            failed_tg = False
            for check in self.tg_checkers:
                if not check.feasible(option):
                    if not tg_escaped:
                        elig.set_task_group_eligibility(
                            False, self.tg, option.computed_class
                        )
                    failed_tg = True
                    break
            if failed_tg:
                continue

            if not tg_escaped and tg_unknown:
                elig.set_task_group_eligibility(
                    True, self.tg, option.computed_class
                )

            if not self._available(option):
                continue

            return option

    def _available(self, option: Node) -> bool:
        for check in self.tg_available:
            if not check.feasible(option):
                return False
        return True
