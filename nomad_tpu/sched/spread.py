"""Spread scoring (reference scheduler/spread.go).

Boost per spread attribute: ``((desired - used) / desired) * weight/sum``
with target percents of tg.count (spread.go:163), or the even-spread
min/max-delta algorithm when no targets are given (spread.go:178); the
total is appended to the score list only when non-zero.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..structs import Job, Node, Spread, TaskGroup
from .context import EvalContext
from .propertyset import PropertySet, get_property
from .rank import RankedNode

IMPLICIT_TARGET = "*"


def compute_spread_info(spreads, total_count: int):
    """Attribute-keyed desired counts + weights (reference
    spread.go:232 computeSpreadInfo).  Later stanzas overwrite earlier
    ones per attribute — reference behavior when job- and group-level
    spreads share an attribute — while every stanza's weight counts
    toward the sum.  Returns (infos, sum_weights)."""
    infos: Dict[str, dict] = {}
    sum_weights = 0
    for spread in spreads:
        desired_counts: Dict[str, float] = {}
        sum_desired = 0.0
        for target in spread.targets:
            desired = (float(target.percent) / 100.0) * float(
                total_count
            )
            desired_counts[target.value] = desired
            sum_desired += desired
        if 0 < sum_desired < float(total_count):
            desired_counts[IMPLICIT_TARGET] = (
                float(total_count) - sum_desired
            )
        infos[spread.attribute] = {
            "weight": spread.weight,
            "desired_counts": desired_counts,
        }
        sum_weights += spread.weight
    return infos, sum_weights


class SpreadIterator:
    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source
        self.job: Optional[Job] = None
        self.tg: Optional[TaskGroup] = None
        self.job_spreads: List[Spread] = []
        self.tg_spread_info: Dict[str, Dict[str, dict]] = {}
        self.sum_spread_weights = 0
        self.has_spread = False
        self.group_property_sets: Dict[str, List[PropertySet]] = {}

    def reset(self) -> None:
        self.source.reset()
        for sets in self.group_property_sets.values():
            for ps in sets:
                ps.populate_proposed()

    def set_job(self, job: Job) -> None:
        self.job = job
        if job.spreads:
            self.job_spreads = list(job.spreads)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        if tg.name not in self.group_property_sets:
            sets: List[PropertySet] = []
            for spread in self.job_spreads:
                pset = PropertySet(self.ctx, self.job)
                pset.set_target_attribute(spread.attribute, tg.name)
                sets.append(pset)
            for spread in tg.spreads:
                pset = PropertySet(self.ctx, self.job)
                pset.set_target_attribute(spread.attribute, tg.name)
                sets.append(pset)
            self.group_property_sets[tg.name] = sets
        self.has_spread = bool(self.group_property_sets[tg.name])
        if tg.name not in self.tg_spread_info:
            self._compute_spread_info(tg)

    def has_spreads(self) -> bool:
        return self.has_spread

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None or not self.has_spreads():
                return option

            tg_name = self.tg.name
            property_sets = self.group_property_sets[tg_name]
            total_spread_score = 0.0
            for pset in property_sets:
                nvalue, error_msg, used_count = pset.used_count(
                    option.node, tg_name
                )
                # include this prospective placement (spread.go:123)
                used_count += 1
                if error_msg:
                    total_spread_score -= 1.0
                    continue
                spread_details = self.tg_spread_info[tg_name].get(
                    pset.target_attribute
                )
                if spread_details is None:
                    continue
                desired_counts = spread_details["desired_counts"]
                if not desired_counts:
                    total_spread_score += even_spread_score_boost(
                        pset, option.node
                    )
                else:
                    desired = desired_counts.get(nvalue)
                    if desired is None:
                        desired = desired_counts.get(IMPLICIT_TARGET)
                        if desired is None:
                            total_spread_score -= 1.0
                            continue
                    spread_weight = (
                        float(spread_details["weight"])
                        / float(self.sum_spread_weights)
                    )
                    boost = (
                        (desired - float(used_count)) / desired
                    ) * spread_weight
                    total_spread_score += boost

            if total_spread_score != 0.0:
                option.scores.append(total_spread_score)
                self.ctx.metrics.score_node(
                    option.node, "allocation-spread", total_spread_score
                )
            return option

    def _compute_spread_info(self, tg: TaskGroup) -> None:
        """(reference spread.go:232 computeSpreadInfo)"""
        combined = list(tg.spreads) + list(self.job_spreads)
        infos, sum_weights = compute_spread_info(combined, tg.count)
        self.sum_spread_weights += sum_weights
        self.tg_spread_info[tg.name] = infos


def even_spread_score_boost(pset: PropertySet, option: Node) -> float:
    """(reference spread.go:178 evenSpreadScoreBoost)"""
    combined_use = pset.get_combined_use_map()
    if not combined_use:
        return 0.0
    nvalue, ok = get_property(option, pset.target_attribute)
    if not ok:
        return -1.0
    current = combined_use.get(nvalue, 0)
    min_count = 0
    max_count = 0
    for value in combined_use.values():
        if min_count == 0 or value < min_count:
            min_count = value
        if max_count == 0 or value > max_count:
            max_count = value

    if min_count == 0:
        delta_boost = -1.0
    else:
        delta = min_count - current
        delta_boost = float(delta) / float(min_count)
    if current != min_count:
        return delta_boost
    elif min_count == max_count:
        return -1.0
    elif min_count == 0:
        return 1.0
    delta = max_count - min_count
    return float(delta) / float(min_count)
