"""Core scheduler: garbage collection of terminal state
(reference nomad/core_sched.go:34; eval type "_core",
structs.go:3707).

Registered like any scheduler and driven by periodic `_core` evals the
leader enqueues (reference leader.go schedulePeriodic), so GC flows
through the same broker/worker machinery as placements.
"""
from __future__ import annotations

import time
from typing import List, Optional

from ..structs import (
    Evaluation,
    EVAL_STATUS_COMPLETE,
    JOB_STATUS_DEAD,
)

# GC job IDs carried in the eval's job_id (reference core_sched.go:43-60)
CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_DEPLOYMENT_GC = "deployment-gc"
CORE_JOB_FORCE_GC = "force-gc"

DEFAULT_EVAL_GC_THRESHOLD_S = 3600.0
DEFAULT_JOB_GC_THRESHOLD_S = 4 * 3600.0
DEFAULT_NODE_GC_THRESHOLD_S = 24 * 3600.0
DEFAULT_DEPLOYMENT_GC_THRESHOLD_S = 3600.0


class CoreScheduler:
    def __init__(
        self,
        state,
        planner,
        eval_gc_threshold: float = DEFAULT_EVAL_GC_THRESHOLD_S,
        job_gc_threshold: float = DEFAULT_JOB_GC_THRESHOLD_S,
        node_gc_threshold: float = DEFAULT_NODE_GC_THRESHOLD_S,
        deployment_gc_threshold: float = DEFAULT_DEPLOYMENT_GC_THRESHOLD_S,
        **_kwargs,
    ) -> None:
        self.snap = state
        self.planner = planner
        self.eval_gc_threshold = eval_gc_threshold
        self.job_gc_threshold = job_gc_threshold
        self.node_gc_threshold = node_gc_threshold
        self.deployment_gc_threshold = deployment_gc_threshold

    # the snapshot delegates to the live store in this control plane;
    # GC mutates through the store directly (the reference applies raft
    # dereg/reap messages)
    @property
    def store(self):
        return self.snap._store

    def process(self, evaluation: Evaluation) -> None:
        job = evaluation.job_id
        force = job == CORE_JOB_FORCE_GC
        if job in (CORE_JOB_EVAL_GC,) or force:
            self.eval_gc(force)
        if job in (CORE_JOB_JOB_GC,) or force:
            self.job_gc(force)
        if job in (CORE_JOB_DEPLOYMENT_GC,) or force:
            self.deployment_gc(force)
        if job in (CORE_JOB_NODE_GC,) or force:
            self.node_gc(force)
        evaluation.status = EVAL_STATUS_COMPLETE
        self.planner.update_eval(evaluation)

    # ------------------------------------------------------------------

    def _old_enough(self, ts: float, threshold: float, force: bool) -> bool:
        return force or (time.time() - ts) > threshold

    def eval_gc(self, force: bool = False) -> int:
        """Reap terminal evals and their terminal allocs
        (reference core_sched.go:228 evalGC)."""
        store = self.store
        reaped = 0
        for ev in list(store.evals.values()):
            if not ev.terminal_status():
                continue
            if not self._old_enough(
                ev.modify_time, self.eval_gc_threshold, force
            ):
                continue
            allocs = store.allocs_by_eval(ev.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            for alloc in allocs:
                store.allocs.pop(alloc.id, None)
                store._allocs_by_node.get(alloc.node_id, set()).discard(
                    alloc.id
                )
                store._allocs_by_job.get(
                    (alloc.namespace, alloc.job_id), set()
                ).discard(alloc.id)
            store.delete_eval(ev.id)
            reaped += 1
        return reaped

    def job_gc(self, force: bool = False) -> int:
        """Reap dead jobs whose evals/allocs are all terminal
        (reference core_sched.go:90 jobGC)."""
        store = self.store
        reaped = 0
        for job in list(store.iter_jobs()):
            status = store.derive_job_status(job.namespace, job.id)
            if status != JOB_STATUS_DEAD or job.is_periodic():
                continue
            if not self._old_enough(
                job.submit_time, self.job_gc_threshold, force
            ):
                continue
            allocs = store.allocs_by_job(job.namespace, job.id)
            evals = store.evals_by_job(job.namespace, job.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            if any(not e.terminal_status() for e in evals):
                continue
            for alloc in allocs:
                store.allocs.pop(alloc.id, None)
                store._allocs_by_node.get(alloc.node_id, set()).discard(
                    alloc.id
                )
            for ev in evals:
                store.delete_eval(ev.id)
            store.delete_job(job.namespace, job.id)
            reaped += 1
        return reaped

    def deployment_gc(self, force: bool = False) -> int:
        """(reference core_sched.go deploymentGC)"""
        store = self.store
        reaped = 0
        for d in list(store.deployments.values()):
            if d.active():
                continue
            if not self._old_enough(0.0, self.deployment_gc_threshold, force):
                continue
            store.deployments.pop(d.id, None)
            store._deployments_by_job.get(
                (d.namespace, d.job_id), set()
            ).discard(d.id)
            reaped += 1
        return reaped

    def node_gc(self, force: bool = False) -> int:
        """Reap down nodes with no allocs
        (reference core_sched.go nodeGC)."""
        store = self.store
        reaped = 0
        for node in list(store.iter_nodes()):
            if node.status != "down":
                continue
            if not self._old_enough(
                node.status_updated_at, self.node_gc_threshold, force
            ):
                continue
            if store.allocs_by_node(node.id):
                continue
            store.delete_node(node.id)
            reaped += 1
        return reaped
