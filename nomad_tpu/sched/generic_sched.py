"""Generic (service/batch) scheduler (reference scheduler/generic_sched.go).

`Process(eval)` runs the retry loop (5 service / 2 batch attempts),
reconciles desired vs actual state, computes placements through a Stack —
either the oracle iterator chain or the vectorized TPU stack — and submits
the plan, creating blocked/follow-up evals on failure.
"""
from __future__ import annotations

import time as _time
from dataclasses import replace as _replace
from typing import Dict, List, Optional

from ..structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_RUN,
    AllocatedResources,
    AllocatedSharedResources,
    Allocation,
    AllocMetric,
    Evaluation,
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_COMPLETE,
    EVAL_TRIGGER_MAX_PLANS,
    JOB_TYPE_BATCH,
    Job,
    Node,
    Plan,
    PlanResult,
    RescheduleEvent,
    RescheduleTracker,
    TaskGroup,
)
from .context import EvalContext
from .reconcile import (
    AllocReconciler,
    BLOCKED_EVAL_FAILED_PLACEMENTS,
    BLOCKED_EVAL_MAX_PLAN_DESC,
)
from .scheduler import SetStatusError
from .stack import GenericStack, SelectOptions
from .util import (
    adjust_queued_allocations,
    generic_alloc_update_fn,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2
MAX_PAST_RESCHEDULE_EVENTS = 5

SUPPORTED_TRIGGERS = {
    "job-register",
    "job-deregister",
    "node-drain",
    "node-update",
    "alloc-stop",
    "rolling-update",
    "queued-allocs",
    "periodic-job",
    "max-plan-attempts",
    "deployment-watcher",
    "alloc-failure",
    "failed-follow-up",
    "preemption",
    "job-scaling",
}


class GenericScheduler:
    def __init__(
        self, state, planner, batch: bool, use_tpu: Optional[bool] = None,
        seed: Optional[int] = None, speculative: bool = False,
    ) -> None:
        self.state = state
        self.planner = planner
        self.batch = batch
        self.seed = seed
        # snapshot-pinned, side-effect-free replay mode: `state` is an
        # immutable wave snapshot and `planner` a capturing facade (the
        # BatchWorker's speculative planner) — the flag flows into the
        # EvalContext so stacks can refuse paths that read beyond the
        # conflict-checkable set
        self.speculative = speculative
        if use_tpu is None:
            use_tpu = state.scheduler_config().tpu_scheduler_enabled
        self.use_tpu = use_tpu

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack = None
        self.deployment = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}
        self.followup_evals: List[Evaluation] = []

    # ------------------------------------------------------------------

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        if evaluation.triggered_by not in SUPPORTED_TRIGGERS:
            desc = (
                f"scheduler cannot handle '{evaluation.triggered_by}' "
                "evaluation reason"
            )
            set_status(
                self.planner, evaluation, None, self.blocked,
                self.failed_tg_allocs, "failed", desc,
                self.queued_allocs, self._deployment_id(),
            )
            return

        limit = (
            MAX_BATCH_SCHEDULE_ATTEMPTS
            if self.batch
            else MAX_SERVICE_SCHEDULE_ATTEMPTS
        )
        try:
            retry_max(
                limit,
                self._process_once,
                lambda: progress_made(self.plan_result),
            )
        except SetStatusError as err:
            # no forward progress: block to retry when resources free up
            self._create_blocked_eval(plan_failure=True)
            set_status(
                self.planner, self.eval, None, self.blocked,
                self.failed_tg_allocs, err.eval_status, str(err),
                self.queued_allocs, self._deployment_id(),
            )
            return

        if (
            self.eval.status == EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
        ):
            e = self.ctx.eligibility
            new_eval = _replace(self.eval)
            new_eval.escaped_computed_class = e.has_escaped()
            new_eval.class_eligibility = e.get_classes()
            new_eval.quota_limit_reached = e.quota_reached
            self.planner.reblock_eval(new_eval)
            return

        set_status(
            self.planner, self.eval, None, self.blocked,
            self.failed_tg_allocs, EVAL_STATUS_COMPLETE, "",
            self.queued_allocs, self._deployment_id(),
        )

    def _deployment_id(self) -> str:
        return self.deployment.id if self.deployment is not None else ""

    def _create_blocked_eval(self, plan_failure: bool) -> None:
        e = self.ctx.eligibility if self.ctx is not None else None
        escaped = e.has_escaped() if e else False
        class_eligibility = {}
        if e and not escaped:
            class_eligibility = e.get_classes()
        self.blocked = self.eval.create_blocked_eval(
            class_eligibility, escaped, e.quota_reached if e else ""
        )
        if plan_failure:
            self.blocked.triggered_by = EVAL_TRIGGER_MAX_PLANS
            self.blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
        else:
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(self.blocked)

    # ------------------------------------------------------------------

    def _process_once(self) -> bool:
        """(reference generic_sched.go:216 process)"""
        self.job = self.state.job_by_id(
            self.eval.namespace, self.eval.job_id
        )
        self.queued_allocs = {}
        self.followup_evals = []

        self.plan = self.eval.make_plan(self.job)

        if not self.batch:
            self.deployment = self.state.latest_deployment_by_job(
                self.eval.namespace, self.eval.job_id
            )

        self.failed_tg_allocs = {}
        self.ctx = EvalContext(
            self.state, self.plan, seed=self.seed,
            speculative=self.speculative,
        )
        self.stack = self._make_stack()
        if self.job is not None and not self.job.stopped():
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        delay_instead = (
            bool(self.followup_evals) and self.eval.wait_until == 0.0
        )

        if (
            self.eval.status != EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
            and self.blocked is None
            and not delay_instead
        ):
            self._create_blocked_eval(plan_failure=False)

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True

        if delay_instead:
            for followup in self.followup_evals:
                followup.previous_eval = self.eval.id
                self.planner.create_eval(followup)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, _expected, _actual = result.full_commit(self.plan)
        if not full_commit:
            return False
        return True

    def _make_stack(self):
        if self.use_tpu:
            from .tpu_stack import TPUGenericStack

            return TPUGenericStack(self.batch, self.ctx, seed=self.seed)
        return GenericStack(self.batch, self.ctx)

    # ------------------------------------------------------------------

    def _compute_job_allocs(self) -> None:
        """(reference generic_sched.go:332 computeJobAllocs)"""
        allocs = self.state.allocs_by_job(
            self.eval.namespace, self.eval.job_id
        )
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        reconciler = AllocReconciler(
            generic_alloc_update_fn(self.ctx, self.stack, self.eval.id),
            self.batch,
            self.eval.job_id,
            self.job,
            self.deployment,
            allocs,
            tainted,
            self.eval.id,
        )
        results = reconciler.compute()

        if self.eval.annotate_plan:
            self.plan.annotations = {
                "desired_tg_updates": results.desired_tg_updates
            }

        self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates

        for evals in results.desired_followup_evals.values():
            self.followup_evals.extend(evals)

        if results.deployment is not None:
            self.deployment = results.deployment

        for stop in results.stop:
            self.plan.append_stopped_alloc(
                stop.alloc, stop.status_description, stop.client_status
            )
            if stop.followup_eval_id:
                self.plan.node_update[stop.alloc.node_id][-1].followup_eval_id = (
                    stop.followup_eval_id
                )

        deployment_id = self._deployment_id()
        for update in results.inplace_update:
            if update.deployment_id != deployment_id:
                update.deployment_id = deployment_id
                update.deployment_status = None
            self.plan.append_alloc(update)

        for update in results.attribute_updates.values():
            self.plan.append_alloc(update)

        if not results.place and not results.destructive_update:
            if self.job is not None:
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for p in results.place:
            self.queued_allocs[p.task_group.name] = (
                self.queued_allocs.get(p.task_group.name, 0) + 1
            )
        for d in results.destructive_update:
            self.queued_allocs[d.place_task_group.name] = (
                self.queued_allocs.get(d.place_task_group.name, 0) + 1
            )

        self._compute_placements(
            list(results.destructive_update), list(results.place)
        )

    # ------------------------------------------------------------------

    def _compute_placements(self, destructive, place) -> None:
        """(reference generic_sched.go:468 computePlacements)"""
        nodes, by_dc = ready_nodes_in_dcs(
            self.state, self.job.datacenters
        )
        deployment_id = ""
        if self.deployment is not None and self.deployment.active():
            deployment_id = self.deployment.id

        self.stack.set_nodes(nodes)
        now = _time.time()

        for results in (destructive, place):
            for missing in results:
                tg = missing.task_group

                # coalesce failures per task group
                metric = self.failed_tg_allocs.get(tg.name)
                if metric is not None:
                    metric.coalesced_failures += 1
                    continue

                preferred_node = self._find_preferred_node(missing)

                stop_prev, stop_prev_desc = missing.stop_previous_alloc()
                prev_allocation = missing.previous_alloc
                if stop_prev:
                    self.plan.append_stopped_alloc(
                        prev_allocation, stop_prev_desc
                    )

                select_options = get_select_options(
                    prev_allocation, preferred_node
                )
                t_select = _time.monotonic()
                option = self._select_next_option(tg, select_options)
                # real per-TG allocation latency, reported by the plan
                # API and /v1/evaluation/<id>/placement (reference
                # structs.go AllocMetric.AllocationTime)
                self.ctx.metrics.allocation_time_s = (
                    _time.monotonic() - t_select
                )

                self.ctx.metrics.nodes_available = by_dc

                if option is not None:
                    resources = AllocatedResources(
                        tasks=option.task_resources,
                        shared=AllocatedSharedResources(
                            disk_mb=tg.ephemeral_disk.size_mb
                        ),
                    )
                    if option.alloc_resources is not None:
                        resources.shared.networks = (
                            option.alloc_resources.networks
                        )
                        resources.shared.ports = (
                            option.alloc_resources.ports
                        )
                    alloc = Allocation(
                        namespace=self.job.namespace,
                        eval_id=self.eval.id,
                        name=missing.name,
                        job_id=self.job.id,
                        job=self.job,
                        task_group=tg.name,
                        metrics=self.ctx.metrics,
                        node_id=option.node.id,
                        node_name=option.node.name,
                        deployment_id=deployment_id,
                        allocated_resources=resources,
                        desired_status=ALLOC_DESIRED_RUN,
                        client_status=ALLOC_CLIENT_STATUS_PENDING,
                    )
                    if prev_allocation is not None:
                        alloc.previous_allocation = prev_allocation.id
                        if missing.is_rescheduling():
                            update_reschedule_tracker(
                                alloc, prev_allocation, now
                            )
                    if missing.canary and self.deployment is not None:
                        from ..structs import AllocDeploymentStatus

                        alloc.deployment_status = AllocDeploymentStatus(
                            canary=True
                        )
                    self._handle_preemptions(option, alloc)
                    self.plan.append_alloc(alloc)
                else:
                    self.failed_tg_allocs[tg.name] = self.ctx.metrics
                    if stop_prev:
                        updates = self.plan.node_update.get(
                            prev_allocation.node_id, []
                        )
                        self.plan.node_update[prev_allocation.node_id] = [
                            a for a in updates if a.id != prev_allocation.id
                        ]

    def _find_preferred_node(self, place) -> Optional[Node]:
        prev = place.previous_alloc
        if prev is not None and place.task_group.ephemeral_disk.sticky:
            node = self.state.node_by_id(prev.node_id)
            if node is not None and node.ready():
                return node
        return None

    def _select_next_option(self, tg: TaskGroup, select_options):
        option = self.stack.select(tg, select_options)
        config = self.state.scheduler_config()
        if self.job.type == JOB_TYPE_BATCH:
            enable_preemption = (
                config.preemption_config.batch_scheduler_enabled
            )
        else:
            enable_preemption = (
                config.preemption_config.service_scheduler_enabled
            )
        if option is None and enable_preemption:
            select_options.preempt = True
            option = self.stack.select(tg, select_options)
        return option

    def _handle_preemptions(self, option, alloc: Allocation) -> None:
        if option.preempted_allocs is None:
            return
        preempted_ids = []
        for stop in option.preempted_allocs:
            self.plan.append_preempted_alloc(stop, alloc.id)
            preempted_ids.append(stop.id)


def get_select_options(
    prev_allocation: Optional[Allocation],
    preferred_node: Optional[Node],
) -> SelectOptions:
    """(reference generic_sched.go:642 getSelectOptions)"""
    options = SelectOptions()
    if prev_allocation is not None:
        penalty = set()
        if prev_allocation.client_status == ALLOC_CLIENT_STATUS_FAILED:
            penalty.add(prev_allocation.node_id)
        if prev_allocation.reschedule_tracker is not None:
            for event in prev_allocation.reschedule_tracker.events:
                penalty.add(event.prev_node_id)
        options.penalty_node_ids = penalty
    if preferred_node is not None:
        options.preferred_nodes = [preferred_node]
    return options


def update_reschedule_tracker(
    alloc: Allocation, prev: Allocation, now: float
) -> None:
    """(reference generic_sched.go:666 updateRescheduleTracker)"""
    policy = prev.reschedule_policy()
    events: List[RescheduleEvent] = []
    if prev.reschedule_tracker is not None:
        if policy is not None and policy.attempts > 0:
            interval = policy.interval_s
            for event in prev.reschedule_tracker.events:
                if interval > 0 and now - event.reschedule_time <= interval:
                    events.append(event)
        else:
            events = list(
                prev.reschedule_tracker.events[-MAX_PAST_RESCHEDULE_EVENTS:]
            )
    next_delay = prev.next_delay()
    events.append(
        RescheduleEvent(
            reschedule_time=now,
            prev_alloc_id=prev.id,
            prev_node_id=prev.node_id,
            delay_s=next_delay,
        )
    )
    alloc.reschedule_tracker = RescheduleTracker(events=events)


def ServiceScheduler(state, planner, **kwargs) -> GenericScheduler:
    return GenericScheduler(state, planner, batch=False, **kwargs)


def BatchScheduler(state, planner, **kwargs) -> GenericScheduler:
    return GenericScheduler(state, planner, batch=True, **kwargs)
