"""TPU placement stacks: the vectorized backend behind the same `Stack`
surface as the oracle chain (reference scheduler/stack.go).

Division of labor per SURVEY.md section 7:

* **Device (jit kernel, ops/score.py)** — fit masks + all scoring terms
  over every candidate node at once, plus the exact emulation of the
  reference's shuffled limited-walk selection.
* **Host, once per (job, task group)** — constraint compilation to LUT
  masks (ops/constraints.py), affinity weight vectors, spread desired
  counts: tiny vocab-sized work.
* **Host, once per placement** — plan-delta vectors (proposed usage,
  anti-affinity collisions, distinct_hosts), spread use counts, and exact
  port/device assignment for the single *winning* node via the oracle
  BinPackIterator (rank.py) — mirroring how the reference does the
  combinatorial port/device assignment inside binpack only for nodes it
  actually visits.  If the winner fails exact verification (e.g. a port
  collision the count-based mask could not see), the node is masked and
  the kernel re-runs: the recheck loop the reference performs in the plan
  applier (plan_apply.go:629), pulled forward.

Preemption mode (`options.preempt`) keeps the same vectorized walk:
fit masks + scores for every node come from the shared vector math, and
only nodes whose fit failed get the exact per-node preemption
evaluation (oracle BinPackIterator with evict=True, its greedy inner
scan vectorized in sched/preemption.py), whose exact scores — binpack
after eviction plus the logistic net-priority term (rank.go:714) — are
spliced into the score vector before the limited-walk emulation picks
the winner (SURVEY section 7.1 step 5).

Known divergence from the oracle (documented, intentional): when a
computed class is memoized eligible but a transient availability check
(CSI plugin health) fails, the reference aborts the whole walk
(feasible.go:1080 returns nil); the mask path simply excludes the node
and keeps looking, which can place where the reference would block.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from ..explain import EXPLAIN
from ..ops.batch import BatchInputs, plan_picks_full, pow2_bucket
from ..ops.constraints import MaskCompiler
from ..ops.score import (
    NO_NODE,
    PolicyTerms,
    ScoreInputs,
    score_and_select_packed,
)
from ..structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    Job,
    Node,
    NodeScoreMeta,
    TaskGroup,
)
from .context import (
    CLASS_ELIGIBLE,
    CLASS_ESCAPED,
    CLASS_INELIGIBLE,
    CLASS_UNKNOWN,
    EvalContext,
)
from .feasible import (
    FILTER_CLASS_INELIGIBLE,
    FILTER_CONSTRAINT_CSI_VOLUMES,
    FILTER_CONSTRAINT_DEVICES,
    FILTER_CONSTRAINT_DRIVERS,
    FILTER_CONSTRAINT_HOST_VOLUMES,
    FILTER_CONSTRAINT_NETWORK,
)
from .propertyset import PropertySet
from .rank import BinPackIterator, RankedNode, StaticRankIterator
from .stack import (
    GenericStack,
    SelectOptions,
    SystemStack,
    compute_visit_limit,
    task_group_constraints,
)

INT32_MAX = 2**31 - 1
LOOKAHEAD_MAX = 128  # picks pre-computed per launch

import jax.numpy as jnp  # noqa: E402

from ..ops.score import _limited_walk_argmax  # noqa: E402


@jax.jit
def _walk_only(feasible, scores, perm, limit, n_candidates):
    """The limited-walk emulation over a host-assembled score vector
    (preemption mode: exact per-node preemption scores are spliced in
    host-side; the walk semantics must stay identical to the plain
    path's kernel)."""
    return _limited_walk_argmax(
        feasible, scores, perm, limit, n_candidates
    )

_LA_MISS = object()  # look-ahead cache miss sentinel


class _SingleNodeSource:
    """Feeds exactly one RankedNode into a BinPackIterator."""

    def __init__(self, ranked: RankedNode) -> None:
        self.ranked = ranked
        self.done = False

    def next(self) -> Optional[RankedNode]:
        if self.done:
            return None
        self.done = True
        return self.ranked

    def reset(self) -> None:
        self.done = False


class TPUGenericStack:
    def __init__(
        self, batch: bool, ctx: EvalContext, seed: Optional[int] = None
    ) -> None:
        # exclusive accelerator lock before any backend init (no-op on
        # CPU-only): two jax processes wedge a tunneled chip session
        from ..device_lock import ensure_device_lock

        ensure_device_lock("tpu stack")
        self.batch = batch
        self.ctx = ctx
        self.table = ctx.state.node_table
        self.compiler = MaskCompiler(self.table)
        self.job: Optional[Job] = None
        self.nodes: List[Node] = []
        self.shuffled_nodes: List[Node] = []
        self.candidate_rows: np.ndarray = np.zeros(0, dtype=np.int32)
        self.perm: np.ndarray = np.zeros(0, dtype=np.int32)
        self.limit = 2
        self._static_mask_cache: Dict[Tuple, np.ndarray] = {}
        self._affinity_cache: Dict[Tuple, Tuple[np.ndarray, float]] = {}
        self._spread_psets: Dict[str, List[PropertySet]] = {}
        self._spread_info: Dict[str, Dict] = {}
        self._sum_spread_weights = 0
        self._extra_excluded_rows: Set[int] = set()
        # rotating pull offset: the reference StaticIterator keeps its
        # position across selects (feasible.go:75) so consecutive
        # placements continue round-robin through the shuffled list
        self._offset = 0
        # look-ahead pick cache: one plan_picks_full launch pre-computes
        # the whole placement loop of a task group (VERDICT r1 item 5 —
        # one device round trip per placement is ruinous on a tunnel)
        self._la_rows: Optional[List[int]] = None
        self._la_pulls: List[int] = []
        self._la_idx = 0
        self._la_key: Optional[Tuple] = None
        self._la_counts: Tuple[int, int, int] = (0, 0, 0)
        self._la_generation = -1
        # explain capture's shadow of the FeasibilityWrapper's
        # computed-class memoization.  Deliberately NOT the shared
        # EvalEligibility: that feeds blocked-eval unblocking, and an
        # observability layer must never change scheduler behavior
        # with its opt-out flag
        self._explain_job_elig: Dict[str, int] = {}
        self._explain_tg_elig: Dict[str, Dict[str, int]] = {}

    # ------------------------------------------------------------------

    def set_nodes(self, base_nodes: List[Node]) -> None:
        nodes = list(base_nodes)
        from .feasible import shuffle_nodes

        shuffle_nodes(self.ctx.rng, nodes)
        self.nodes = base_nodes
        self.shuffled_nodes = nodes
        rows = [
            self.table.row_of[n.id]
            for n in nodes
            if n.id in self.table.row_of
        ]
        self.candidate_rows = np.asarray(rows, dtype=np.int32)
        # perm must be a full arena permutation: candidates first, in the
        # shuffled visit order
        present = set(rows)
        perm = rows + [
            r for r in range(self.table.capacity) if r not in present
        ]
        self.perm = np.asarray(perm, dtype=np.int32)
        self.limit = compute_visit_limit(len(nodes), self.batch)
        self._offset = 0
        self._la_rows = None

    def set_job(self, job: Job) -> None:
        if self.job is not None and self.job.version == job.version:
            return
        self.job = job
        self.ctx.eligibility.set_job(job)
        self._la_rows = None
        self._static_mask_cache.clear()
        self._affinity_cache.clear()
        self._spread_psets.clear()
        self._spread_info.clear()
        self._sum_spread_weights = 0
        self._explain_job_elig.clear()
        self._explain_tg_elig.clear()

    # ------------------------------------------------------------------

    def select(
        self, tg: TaskGroup, options: Optional[SelectOptions] = None
    ) -> Optional[RankedNode]:
        # preferred nodes (sticky ephemeral disk) compose WITH preempt
        # mode: the oracle tries the preferred node first — with
        # eviction when preempt is set (stack.py SetNodes + select) —
        # so the narrowing must happen before the preempt branch
        if options is not None and options.preferred_nodes:
            original_rows = self.candidate_rows
            original_perm = self.perm
            preferred_rows = [
                self.table.row_of[n.id]
                for n in options.preferred_nodes
                if n.id in self.table.row_of
            ]
            self.candidate_rows = np.asarray(
                preferred_rows, dtype=np.int32
            )
            present = set(preferred_rows)
            self.perm = np.asarray(
                preferred_rows
                + [
                    r
                    for r in range(self.table.capacity)
                    if r not in present
                ],
                dtype=np.int32,
            )
            options_new = SelectOptions(
                penalty_node_ids=options.penalty_node_ids,
                preferred_nodes=[],
                preempt=options.preempt,
            )
            saved_offset = self._offset
            self._offset = 0
            option = self.select(tg, options_new)
            # the reference resets the source offset when restoring the
            # original node set (stack.go:119-133 SetNodes)
            self.candidate_rows = original_rows
            self.perm = original_perm
            self._offset = 0
            if option is not None:
                return option
            return self.select(tg, options_new)

        if options is not None and options.preempt:
            return self._preempt_select(tg, options)

        self.ctx.reset()
        self._extra_excluded_rows = set()
        out = self._lookahead_serve(tg, options)
        if out is not _LA_MISS:
            return out
        return self._select_vectorized(tg, options)

    # ------------------------------------------------------------------

    def _plan_counts(self) -> Tuple[int, int, int]:
        p = self.ctx.plan
        return (
            sum(len(v) for v in p.node_update.values()),
            sum(len(v) for v in p.node_allocation.values()),
            sum(len(v) for v in p.node_preemptions.values()),
        )

    def _policy_state(self, tg: TaskGroup, dtype=np.float64):
        """The job's resolved policy plus arena-shaped, PRE-SCALED
        term vectors (sched/policy.py, ops/score.py PolicyTerms):
        ``(resolved, tput_term[C] | None, mig_term[C] | None)`` or
        None.  An inert group stays None so the kernel traces only the
        ops the select needs (the identity-weights hot shape is one
        vector add).  The throughput tensor is cached keyed by (table
        epoch, job version, topo generation); the stickiness vector is
        rebuilt per select from the job's live allocs — O(allocs of
        this TG), the same replicated state fan-out followers hold."""
        from .policy import (
            migration_vector,
            resolve,
            sticky_node_ids,
            tput_tensor,
        )

        pol = resolve(self.job)
        if pol is None:
            return None
        tput_term = None
        if pol.has_tput:
            tput_term = pol.tput_coef * tput_tensor(
                pol, self.job, self.table, dtype=dtype
            )
        sticky = sticky_node_ids(pol, self.job, tg.name, self.ctx.state)
        mig_term = None
        if sticky:
            mig_term = pol.mig_coef * migration_vector(
                sticky, self.table, dtype=dtype
            )
        return pol, tput_term, mig_term

    def _lookahead_serve(self, tg: TaskGroup, options):
        """Answer a select from the pre-computed pick cache when the
        scheduler's state advanced exactly as the kernel modelled it:
        same task group and job version, plan grown only by our own
        placements, plain select options.  Each served winner still
        passes exact host verification."""
        if self._la_rows is None:
            return _LA_MISS
        if options is not None and (
            options.penalty_node_ids
            or options.preferred_nodes
            or options.preempt
        ):
            self._la_rows = None
            return _LA_MISS
        if self._la_key != (
            tg.name, self.job.version if self.job else None
        ):
            self._la_rows = None
            return _LA_MISS
        if self.table.generation != self._la_generation:
            self._la_rows = None
            return _LA_MISS
        nu, na, npre = self._plan_counts()
        enu, ena, enpre = self._la_counts
        if nu != enu or npre != enpre or na != ena + self._la_idx:
            self._la_rows = None
            return _LA_MISS
        if self._la_idx >= len(self._la_rows):
            self._la_rows = None
            return _LA_MISS
        row = self._la_rows[self._la_idx]
        pulls = self._la_pulls[self._la_idx]
        n_cand = len(self.candidate_rows)
        if row == NO_NODE:
            self._capture_lookahead(tg, pulls)
            self._la_idx += 1
            if n_cand:
                self._offset = (self._offset + pulls) % n_cand
            self._populate_class_eligibility(
                tg, self._static_feasibility(tg)
            )
            self._la_rows = None  # scheduler coalesces after a failure
            return None
        node_id = self.table.node_ids[row]
        option = self._verify_winner(node_id, tg)
        if option is None:
            # count-mask admitted a node exact assignment rejects:
            # poison it and relaunch from current state.  No explain
            # capture here: the rejection's exhaustion was recorded by
            # the verify chain, and the relaunch's walk captures this
            # placement's full metrics with the row poisoned — the
            # serial pull accounting exactly
            self._extra_excluded_rows.add(row)
            self._la_rows = None
            return _LA_MISS
        self._capture_lookahead(tg, pulls)
        self._la_idx += 1
        if n_cand:
            self._offset = (self._offset + pulls) % n_cand
        return option

    # ------------------------------------------------------------------

    def _preempt_select(self, tg, options):
        """Vectorized preemption-mode select (SURVEY §7.1 step 5).

        The normal-fit mask + scores come from the same vectorized
        scoring as the plain path; only nodes whose fit FAILED and
        whose preemptible resource sum covers the shortfall get the
        exact per-node evaluation (oracle BinPackIterator with
        evict=True, whose inner greedy uses the vectorized
        `preemption_distances`).  Their exact scores — binpack after
        eviction + the logistic net-priority term (rank.go:714) — are
        spliced into the score vector before the same limited-walk
        emulation picks the winner, so decisions stay bit-identical to
        the sequential chain without delegating the walk to a shadow
        oracle.

        Known edge divergence: a node whose cpu/mem/disk fit but whose
        ports/devices are exhausted by preemptible allocs initially
        carries its non-evict score in the walk; the verify-retry loop
        corrects it to the evict score only if it wins a round.  If the
        corrected (higher) score would have beaten the winner the
        oracle can pick it where this path does not — detecting such
        nodes up-front would need the exact per-node evaluation for
        every port-constrained node, which is the cost this design
        avoids."""
        from ..structs.funcs import net_priority as _net_priority
        from ..structs.funcs import preemption_score

        C = self.table.capacity
        self.ctx.reset()
        checks, static_mask = self._static_checks(tg)
        candidate_mask = np.zeros(C, dtype=bool)
        candidate_mask[self.candidate_rows] = True
        d_cpu, d_mem, d_disk, collisions, job_rows, job_tg_rows = (
            self._plan_adjusted_state(tg)
        )
        mask = candidate_mask & static_mask & self.table.active
        csi_mask = self._csi_feasibility(tg)
        if csi_mask is not None:
            mask &= csi_mask
        # NOTE: _extra_excluded_rows (exact non-evict rejections from
        # the preceding plain select) are deliberately NOT applied —
        # the oracle's preempt pass re-evaluates those nodes with
        # eviction, and so does the verify-retry loop below
        job_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in self.job.constraints
        )
        tg_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in tg.constraints
        )
        dh_rows: Set[int] = set()
        if job_distinct:
            dh_rows = {int(r) for r in job_rows}
        elif tg_distinct:
            dh_rows = {int(r) for r in job_tg_rows}
        if dh_rows:
            mask[list(dh_rows)] = False
        dp_mask, dp_psets = self._distinct_property_state(tg)
        mask &= dp_mask

        penalty = np.zeros(C, dtype=bool)
        if options is not None and options.penalty_node_ids:
            for node_id in options.penalty_node_ids:
                row = self.table.row_of.get(node_id)
                if row is not None:
                    penalty[row] = True
        affinity_vec = self._affinity_vector(tg)
        spread_vec, has_spreads = self._spread_vector(tg)
        has_affinities = bool(
            list(self.job.affinities)
            or list(tg.affinities)
            or any(t.affinities for t in tg.tasks)
        )
        policy_state = self._policy_state(tg)
        limit = (
            INT32_MAX
            if (has_affinities or has_spreads or policy_state is not None)
            else self.limit
        )
        ask_cpu = float(sum(t.resources.cpu for t in tg.tasks))
        ask_mem = float(sum(t.resources.memory_mb for t in tg.tasks))
        ask_disk = float(tg.ephemeral_disk.size_mb)

        used_cpu = self.table.cpu_used + d_cpu
        used_mem = self.table.mem_used + d_mem
        used_disk = self.table.disk_used + d_disk
        fit = (
            (used_cpu + ask_cpu <= self.table.cpu_total)
            & (used_mem + ask_mem <= self.table.mem_total)
            & (used_disk + ask_disk <= self.table.disk_total)
        )

        # exact scores for normally-fitting nodes (same math as the
        # kernel: canonical f32-rounded pow, identical append order)
        scores = np.full(C, -np.inf)
        feasible = mask & fit
        preempt_options: dict = {}
        # rows the exact evict chain already evaluated (its metric
        # side effects — exhaustion dims, binpack/preemption scores —
        # land on ctx.metrics through the shared BinPackIterator, so
        # the explain capture must not double-attribute them)
        evict_checked: Set[int] = set()
        # vector fitness for fitting nodes (canonical f32-rounded pow)
        from ..structs.funcs import pow10_np

        safe_cpu = np.where(
            self.table.cpu_total > 0, self.table.cpu_total, 1.0
        )
        safe_mem = np.where(
            self.table.mem_total > 0, self.table.mem_total, 1.0
        )
        free_cpu = 1.0 - (used_cpu + ask_cpu) / safe_cpu
        free_mem = 1.0 - (used_mem + ask_mem) / safe_mem
        base = pow10_np(free_cpu) + pow10_np(free_mem)
        spread_fit_alg = (
            self.ctx.state.scheduler_config().effective_scheduler_algorithm()
            == "spread"
        )
        if spread_fit_alg:
            fitness = np.clip(base - 2.0, 0.0, 18.0)
        else:
            fitness = np.clip(20.0 - base, 0.0, 18.0)

        # policy term vectors (the serial PolicyIterator sits between
        # spread and preemption scoring, so these append after spread
        # and before the preemption term everywhere below)
        tput_term = mig_term = None
        pol = None
        if policy_state is not None:
            pol, tput_term, mig_term = policy_state

        def combine(row, first_terms):
            terms = list(first_terms)
            if collisions[row] > 0:
                terms.append(
                    -(float(collisions[row]) + 1.0) / float(tg.count)
                )
            if penalty[row]:
                terms.append(-1.0)
            if affinity_vec[row] != 0.0:
                terms.append(float(affinity_vec[row]))
            if spread_vec[row] != 0.0:
                terms.append(float(spread_vec[row]))
            if tput_term is not None:
                terms.append(float(tput_term[row]))
            if mig_term is not None and mig_term[row] != 0.0:
                terms.append(float(mig_term[row]))
            return terms

        # vectorized mean-combine for fitting nodes (same term order
        # and append conditions as the kernel: ops/batch.py step)
        has_coll = collisions > 0
        anti_v = np.where(
            has_coll,
            -(collisions.astype(np.float64) + 1.0) / float(tg.count),
            0.0,
        )
        has_aff = affinity_vec != 0.0
        has_spread = spread_vec != 0.0
        sum_v = (
            fitness / 18.0
            + anti_v
            - penalty.astype(np.float64)
            + np.where(has_aff, affinity_vec, 0.0)
            + np.where(has_spread, spread_vec, 0.0)
        )
        count_v = (
            1.0
            + has_coll.astype(np.float64)
            + penalty.astype(np.float64)
            + has_aff.astype(np.float64)
            + has_spread.astype(np.float64)
        )
        if tput_term is not None:
            sum_v = sum_v + tput_term
            count_v = count_v + 1.0
        if mig_term is not None:
            has_mig = mig_term != 0.0
            sum_v = sum_v + np.where(has_mig, mig_term, 0.0)
            count_v = count_v + has_mig.astype(np.float64)
        scores[feasible] = (sum_v / count_v)[feasible]

        # preemption evaluation for masked nodes that did NOT fit.
        # Cheap shortfall pre-filter first: a node whose preemptible
        # allocs (priority <= job.priority - delta, other jobs) cannot
        # cover the resource shortfall can never preempt its way to
        # feasibility — skip the exact evaluation
        # (preemption.go:666 filterAndGroupPreemptibleAllocs criteria).
        from ..structs import PREEMPTION_PRIORITY_DELTA

        for row in np.nonzero(mask & ~fit)[0]:
            node_id = self.table.node_ids[row]
            short_cpu = used_cpu[row] + ask_cpu - self.table.cpu_total[row]
            short_mem = used_mem[row] + ask_mem - self.table.mem_total[row]
            short_disk = (
                used_disk[row] + ask_disk - self.table.disk_total[row]
            )
            pre_cpu = pre_mem = pre_disk = 0.0
            for alloc in self.ctx.proposed_allocs(node_id):
                if alloc.job is None:
                    continue
                if (alloc.namespace, alloc.job_id) == (
                    self.job.namespace, self.job.id,
                ):
                    continue
                if (
                    self.job.priority - alloc.job.priority
                    < PREEMPTION_PRIORITY_DELTA
                ):
                    continue
                r = alloc.comparable_resources()
                pre_cpu += r.cpu
                pre_mem += r.memory_mb
                pre_disk += r.disk_mb
            if (
                pre_cpu < short_cpu
                or pre_mem < short_mem
                or pre_disk < short_disk
            ):
                continue  # provably cannot free enough
            evict_checked.add(int(row))
            option = self._verify_winner(node_id, tg, evict=True)
            if option is None or option.preempted_allocs is None:
                continue  # no viable preemption set: stays infeasible
            # exact score: the single-node chain's appended scores
            # (binpack after eviction, device affinity) + the shared
            # soft terms + the logistic preemption term, mean-combined
            terms = combine(row, list(option.scores))
            netp = _net_priority(
                [
                    a.job.priority
                    for a in option.preempted_allocs
                    if a.job is not None
                ]
            )
            pre_score = preemption_score(netp)
            option.scores.append(pre_score)
            terms.append(pre_score)
            self.ctx.metrics.score_node(
                option.node, "preemption", pre_score
            )
            scores[row] = float(np.mean(terms))
            feasible[row] = True
            preempt_options[row] = option

        # identical limited-walk emulation as the plain path, with the
        # plain path's poison-and-rerun loop: a fitting winner that
        # fails exact verification (ports/devices) gets the evict=True
        # evaluation — the oracle's binpack in preempt mode can
        # device/port-preempt such a node — before being masked out
        n_cand = len(self.candidate_rows)
        cand = self.perm[:n_cand]
        rest = self.perm[n_cand:]
        off = self._offset % n_cand if n_cand else 0
        rotated = np.concatenate(
            [cand[off:], cand[:off], rest]
        ).astype(np.int32)

        def capture(pulls: int) -> None:
            if not EXPLAIN.enabled:
                return
            self._capture_explain(
                tg, rotated, pulls,
                feasible_mask=mask,
                used=(used_cpu, used_mem, used_disk),
                asks=(ask_cpu, ask_mem, ask_disk),
                collisions=collisions,
                penalty=penalty,
                affinity_vec=affinity_vec,
                spread_vec=spread_vec,
                has_affinities=has_affinities,
                has_spreads=has_spreads,
                spread_fit=spread_fit_alg,
                checks=checks,
                csi_mask=csi_mask,
                dh_rows=dh_rows,
                dp_mask=dp_mask,
                dp_psets=dp_psets,
                skip_rows={
                    r for r in evict_checked
                    if r not in preempt_options
                },
                preempt_scored={
                    r: float(scores[r]) for r in preempt_options
                },
                policy_state=policy_state,
            )

        while True:
            chosen_row, _best, _n, pulls = jax.device_get(
                _walk_only(
                    jnp.asarray(feasible),
                    jnp.asarray(scores),
                    jnp.asarray(rotated),
                    jnp.asarray(limit, jnp.int32),
                    jnp.asarray(n_cand, jnp.int32),
                )
            )
            chosen_row, pulls = int(chosen_row), int(pulls)
            if chosen_row == NO_NODE:
                if n_cand:
                    self._offset = (self._offset + pulls) % n_cand
                capture(pulls)
                self._populate_class_eligibility(tg, static_mask)
                return None
            if chosen_row in preempt_options:
                if n_cand:
                    self._offset = (self._offset + pulls) % n_cand
                capture(pulls)
                return preempt_options[chosen_row]
            node_id = self.table.node_ids[chosen_row]
            option = self._verify_winner(node_id, tg)
            if option is not None:
                if n_cand:
                    self._offset = (self._offset + pulls) % n_cand
                capture(pulls)
                return option
            # exact-only dimensions failed: try with eviction
            evict_checked.add(chosen_row)
            option = self._verify_winner(node_id, tg, evict=True)
            if option is not None and option.preempted_allocs:
                terms = combine(chosen_row, list(option.scores))
                netp = _net_priority(
                    [
                        a.job.priority
                        for a in option.preempted_allocs
                        if a.job is not None
                    ]
                )
                pre_score = preemption_score(netp)
                option.scores.append(pre_score)
                terms.append(pre_score)
                self.ctx.metrics.score_node(
                    option.node, "preemption", pre_score
                )
                scores[chosen_row] = float(np.mean(terms))
                preempt_options[chosen_row] = option
                continue  # re-walk with the corrected score
            feasible[chosen_row] = False
            scores[chosen_row] = -np.inf

    # ------------------------------------------------------------------

    def _select_vectorized(
        self, tg: TaskGroup, options: Optional[SelectOptions]
    ) -> Optional[RankedNode]:
        C = self.table.capacity
        dtype = np.float64

        checks, static_mask = self._static_checks(tg)

        candidate_mask = np.zeros(C, dtype=bool)
        candidate_mask[self.candidate_rows] = True

        d_cpu, d_mem, d_disk, collisions, job_rows, job_tg_rows = (
            self._plan_adjusted_state(tg)
        )

        mask = candidate_mask & static_mask & self.table.active
        csi_mask = self._csi_feasibility(tg)
        if csi_mask is not None:
            mask &= csi_mask
        if self._extra_excluded_rows:
            mask[list(self._extra_excluded_rows)] = False

        # distinct_hosts (feasible.go:470)
        job_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in self.job.constraints
        )
        tg_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS for c in tg.constraints
        )
        dh_rows: Set[int] = set()
        if job_distinct:
            dh_rows = {int(r) for r in job_rows}
        elif tg_distinct:
            dh_rows = {int(r) for r in job_tg_rows}
        if dh_rows:
            mask[list(dh_rows)] = False

        # distinct_property (feasible.go:569)
        dp_mask, dp_psets = self._distinct_property_state(tg)
        mask &= dp_mask

        penalty = np.zeros(C, dtype=bool)
        if options is not None and options.penalty_node_ids:
            for node_id in options.penalty_node_ids:
                row = self.table.row_of.get(node_id)
                if row is not None:
                    penalty[row] = True

        affinity_vec = self._affinity_vector(tg)
        spread_vec, has_spreads = self._spread_vector(tg)

        has_affinities = bool(
            list(self.job.affinities)
            or list(tg.affinities)
            or any(t.affinities for t in tg.tasks)
        )
        policy_state = self._policy_state(tg, dtype)
        # policy joins affinity/spread in the unlimited-walk rule
        # (stack.py select: weighted scoring surveys every candidate)
        limit = (
            INT32_MAX
            if (has_affinities or has_spreads or policy_state is not None)
            else self.limit
        )

        ask_cpu = float(sum(t.resources.cpu for t in tg.tasks))
        ask_mem = float(sum(t.resources.memory_mb for t in tg.tasks))
        ask_disk = float(tg.ephemeral_disk.size_mb)

        # rotate the candidate portion of the perm by the accumulated
        # pull offset (StaticIterator round-robin continuation)
        n_cand = len(self.candidate_rows)
        cand = self.perm[:n_cand]
        rest = self.perm[n_cand:]
        off = self._offset % n_cand if n_cand else 0
        rotated = np.concatenate(
            [cand[off:], cand[:off], rest]
        ).astype(np.int32)

        spread_fit_alg = (
            self.ctx.state.scheduler_config().effective_scheduler_algorithm()
            == "spread"
        )
        # look-ahead: when the remaining placement loop is plain (no
        # penalties/spreads/distinct_property), pre-compute the whole
        # pick sequence in ONE launch; subsequent selects answer from
        # the cache (generic_sched.go:468 computePlacements loop)
        use_lookahead = (
            tg.count > 1
            and n_cand > 1
            and not has_spreads
            and policy_state is None
            and (options is None or not options.penalty_node_ids)
            and not any(
                c.operand == CONSTRAINT_DISTINCT_PROPERTY
                for c in list(self.job.constraints) + list(tg.constraints)
            )
        )
        if use_lookahead:
            P = min(LOOKAHEAD_MAX, int(tg.count))
            binp = BatchInputs(
                feasible=mask,
                base_cpu_used=self.table.cpu_used + d_cpu,
                base_mem_used=self.table.mem_used + d_mem,
                base_disk_used=self.table.disk_used + d_disk,
                base_collisions=collisions,
                penalty=penalty,
                affinity_score=affinity_vec,
                perm=rotated,
                ask_cpu=np.float64(ask_cpu),
                ask_mem=np.float64(ask_mem),
                ask_disk=np.float64(ask_disk),
                desired_count=np.int32(tg.count),
                limit=np.int32(limit),
                distinct_hosts=np.bool_(job_distinct or tg_distinct),
            )
            packed = jax.device_get(
                plan_picks_full(
                    self.table.cpu_total,
                    self.table.mem_total,
                    self.table.disk_total,
                    binp,
                    np.int32(n_cand),
                    pow2_bucket(P),
                    spread_fit=spread_fit_alg,
                )
            )
            la_rows, la_pulls = packed[0], packed[1]
            self._la_rows = [int(r) for r in la_rows[:P]]
            self._la_pulls = [int(p) for p in la_pulls[:P]]
            self._la_idx = 0
            self._la_key = (tg.name, self.job.version)
            self._la_counts = self._plan_counts()
            self._la_generation = self.table.generation
            out = self._lookahead_serve(tg, options)
            if out is not _LA_MISS:
                return out
            # first pick failed exact verification: rebuild with the
            # poisoned row excluded
            return self._select_vectorized(tg, options)

        used_cpu = self.table.cpu_used + d_cpu
        used_mem = self.table.mem_used + d_mem
        used_disk = self.table.disk_used + d_disk
        policy_terms = None
        if policy_state is not None:
            _pol, tput_term, mig_term = policy_state
            # both groups inert (armed coefficient, no live allocs
            # yet): skip the PolicyTerms node entirely so the trace —
            # and the compiled-signature cache — match policy-off (the
            # unlimited-walk limit above still applies either way)
            if tput_term is not None or mig_term is not None:
                policy_terms = PolicyTerms(
                    tput_term=tput_term,
                    has_tput=(
                        None
                        if tput_term is None
                        else np.asarray(1.0, dtype)
                    ),
                    mig_term=mig_term,
                )
        inputs = ScoreInputs(
            cpu_total=self.table.cpu_total,
            mem_total=self.table.mem_total,
            disk_total=self.table.disk_total,
            cpu_used=used_cpu,
            mem_used=used_mem,
            disk_used=used_disk,
            feasible=mask,
            collisions=collisions,
            penalty=penalty,
            affinity_score=affinity_vec,
            spread_boost=spread_vec,
            perm=rotated,
            ask_cpu=np.asarray(ask_cpu, dtype),
            ask_mem=np.asarray(ask_mem, dtype),
            ask_disk=np.asarray(ask_disk, dtype),
            desired_count=np.asarray(tg.count, np.int32),
            limit=np.asarray(limit, np.int32),
            n_candidates=np.asarray(n_cand, np.int32),
            policy=policy_terms,
        )
        spread_fit = spread_fit_alg

        def capture(pulls: int) -> None:
            if not EXPLAIN.enabled:
                return
            self._capture_explain(
                tg, rotated, pulls,
                feasible_mask=np.asarray(inputs.feasible),
                used=(used_cpu, used_mem, used_disk),
                asks=(ask_cpu, ask_mem, ask_disk),
                collisions=collisions,
                penalty=penalty,
                affinity_vec=affinity_vec,
                spread_vec=spread_vec,
                has_affinities=has_affinities,
                has_spreads=has_spreads,
                spread_fit=spread_fit,
                checks=checks,
                csi_mask=csi_mask,
                dh_rows=dh_rows,
                dp_mask=dp_mask,
                dp_psets=dp_psets,
                skip_rows=self._extra_excluded_rows,
                policy_state=policy_state,
            )

        while True:
            # one device->host sync for all outputs: device round trips
            # dominate per-select latency on tunneled hardware
            packed = jax.device_get(
                score_and_select_packed(inputs, spread_fit=spread_fit)
            )
            chosen_row, pulls = int(packed[0]), int(packed[1])
            if chosen_row == NO_NODE:
                if n_cand:
                    self._offset = (self._offset + int(pulls)) % n_cand
                capture(int(pulls))
                self._populate_class_eligibility(tg, static_mask)
                return None
            node_id = self.table.node_ids[chosen_row]
            option = self._verify_winner(node_id, tg)
            if option is not None:
                if n_cand:
                    self._offset = (self._offset + int(pulls)) % n_cand
                capture(int(pulls))
                return option
            # count-mask admitted a node exact assignment rejects
            # (e.g. specific port collision): exclude and re-run; the
            # rejected node becomes an infeasible pull, exactly as if
            # binpack had exhausted it mid-walk
            self._extra_excluded_rows.add(chosen_row)
            new_mask = inputs.feasible.copy()
            new_mask[chosen_row] = False
            inputs = inputs._replace(feasible=new_mask)

    # ------------------------------------------------------------------

    def _verify_winner(
        self, node_id: str, tg: TaskGroup, evict: bool = False
    ) -> Optional[RankedNode]:
        """Exact port/device assignment + fit for the winning node via the
        oracle binpack step (rank.py BinPackIterator); with evict=True
        the chain also runs the exact preemption evaluation and attaches
        preempted_allocs."""
        node = self.ctx.state.node_by_id(node_id)
        if node is None:
            return None
        ranked = RankedNode(node=node)
        source = _SingleNodeSource(ranked)
        algorithm = (
            self.ctx.state.scheduler_config().effective_scheduler_algorithm()
        )
        binpack = BinPackIterator(
            self.ctx, source, evict, self.job.priority, algorithm
        )
        binpack.set_job(self.job)
        binpack.set_task_group(tg)
        return binpack.next()

    # -- placement explainability (ISSUE 5) ----------------------------

    def _capture_lookahead(self, tg: TaskGroup, pulls: int) -> None:
        """Explain capture for a pick served from the look-ahead
        cache, so the cache keeps its one-launch-per-group economics
        with the recorder on.  The serve-path consistency checks
        (same job version, table generation, plan advanced exactly as
        the kernel modeled) guarantee a host-side recompute of the
        plan-adjusted state sees precisely what the kernel's chained
        carry saw for this pick; the serve preconditions (no
        penalties, spreads, or distinct_property) zero the terms the
        cache doesn't model."""
        if not EXPLAIN.enabled:
            return
        C = self.table.capacity
        checks, static_mask = self._static_checks(tg)
        candidate_mask = np.zeros(C, dtype=bool)
        candidate_mask[self.candidate_rows] = True
        d_cpu, d_mem, d_disk, collisions, job_rows, job_tg_rows = (
            self._plan_adjusted_state(tg)
        )
        mask = candidate_mask & static_mask & self.table.active
        csi_mask = self._csi_feasibility(tg)
        if csi_mask is not None:
            mask &= csi_mask
        job_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in self.job.constraints
        )
        tg_distinct = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in tg.constraints
        )
        dh_rows: Set[int] = set()
        if job_distinct:
            dh_rows = {int(r) for r in job_rows}
        elif tg_distinct:
            dh_rows = {int(r) for r in job_tg_rows}
        if dh_rows:
            mask[list(dh_rows)] = False
        n_cand = len(self.candidate_rows)
        cand = self.perm[:n_cand]
        rest = self.perm[n_cand:]
        off = self._offset % n_cand if n_cand else 0
        rotated = np.concatenate(
            [cand[off:], cand[:off], rest]
        ).astype(np.int32)
        affinity_vec = self._affinity_vector(tg)
        has_affinities = bool(
            list(self.job.affinities)
            or list(tg.affinities)
            or any(t.affinities for t in tg.tasks)
        )
        spread_fit = (
            self.ctx.state.scheduler_config().effective_scheduler_algorithm()
            == "spread"
        )
        self._capture_explain(
            tg, rotated, int(pulls),
            feasible_mask=mask,
            used=(
                self.table.cpu_used + d_cpu,
                self.table.mem_used + d_mem,
                self.table.disk_used + d_disk,
            ),
            asks=(
                float(sum(t.resources.cpu for t in tg.tasks)),
                float(sum(t.resources.memory_mb for t in tg.tasks)),
                float(tg.ephemeral_disk.size_mb),
            ),
            collisions=collisions,
            penalty=np.zeros(C, dtype=bool),
            affinity_vec=affinity_vec,
            spread_vec=np.zeros(C, dtype=np.float64),
            has_affinities=has_affinities,
            has_spreads=False,
            spread_fit=spread_fit,
            checks=checks,
            csi_mask=csi_mask,
            dh_rows=dh_rows,
            dp_mask=np.ones(C, dtype=bool),
            dp_psets=[],
            skip_rows=self._extra_excluded_rows,
        )

    def _capture_explain(
        self, tg: TaskGroup, rotated: np.ndarray, pulls: int, *,
        feasible_mask, used, asks, collisions, penalty,
        affinity_vec, spread_vec, has_affinities, has_spreads,
        spread_fit, checks, csi_mask, dh_rows, dp_mask, dp_psets,
        skip_rows=frozenset(), preempt_scored=None, policy_state=None,
    ) -> None:
        """Reconstruct the serial iterator chain's AllocMetric from
        the arrays this select already computed: the walk's `pulls`
        bounds the evaluated prefix exactly as the reference's
        StaticIterator would have, every feasible node in it gets the
        per-component score decomposition (vector terms are
        bit-identical to the kernel's, which is bit-identical to the
        host chain's), fit failures get their first exhausted
        dimension (superset order: cpu, memory, disk), and masked
        nodes get first-failure attribution in FeasibilityWrapper
        checker order — including the wrapper's computed-class
        memoization ("computed class ineligible" after the first node
        of a known-bad class, via the shared EvalEligibility).

        ``skip_rows`` are rows whose metric side effects the exact
        verification chain already recorded (poisoned winners, evict
        re-evaluations); ``preempt_scored`` maps rows whose score was
        spliced in by the preemption evaluation to their final
        normalized score."""
        from ..structs.funcs import pow10_np

        metrics = self.ctx.metrics
        metrics.nodes_evaluated += int(pulls)
        if pulls <= 0:
            return
        evaluated = rotated[: int(pulls)]
        used_cpu, used_mem, used_disk = used
        ask_cpu, ask_mem, ask_disk = asks
        fit = (
            (used_cpu + ask_cpu <= self.table.cpu_total)
            & (used_mem + ask_mem <= self.table.mem_total)
            & (used_disk + ask_disk <= self.table.disk_total)
        )
        safe_cpu = np.where(
            self.table.cpu_total > 0, self.table.cpu_total, 1.0
        )
        safe_mem = np.where(
            self.table.mem_total > 0, self.table.mem_total, 1.0
        )
        free_cpu = 1.0 - (used_cpu + ask_cpu) / safe_cpu
        free_mem = 1.0 - (used_mem + ask_mem) / safe_mem
        base = pow10_np(free_cpu) + pow10_np(free_mem)
        if spread_fit:
            fitness = np.clip(base - 2.0, 0.0, 18.0)
        else:
            fitness = np.clip(20.0 - base, 0.0, 18.0)
        preempt_scored = preempt_scored or {}
        state = self.ctx.state
        desired = float(tg.count)
        pol = tput_term = mig_term = None
        if policy_state is not None:
            pol, tput_term, mig_term = policy_state
        # direct NodeScoreMeta writes via a node-id index:
        # AllocMetric.score_node linearly scans score_meta per call,
        # which goes quadratic when unlimited walks (affinities/
        # spreads) score every candidate.  The index starts from the
        # entries the exact verify chain already recorded (the winner)
        meta_by_id = {m.node_id: m for m in metrics.score_meta}

        def meta_for(node_id: str) -> NodeScoreMeta:
            m = meta_by_id.get(node_id)
            if m is None:
                m = NodeScoreMeta(node_id=node_id)
                metrics.score_meta.append(m)
                meta_by_id[node_id] = m
            return m

        for r in (int(x) for x in evaluated):
            if r in skip_rows:
                continue
            node = state.node_by_id(self.table.node_ids[r])
            if node is None:
                continue
            if r in preempt_scored:
                # binpack/devices/preemption terms were recorded by
                # the exact evict chain; add the shared soft terms and
                # the spliced normalized score
                meta = meta_for(node.id)
                self._record_soft_terms(meta.scores, r, collisions,
                                        penalty, affinity_vec,
                                        spread_vec, has_affinities,
                                        has_spreads, desired,
                                        terms=None, pol=pol,
                                        tput_term=tput_term,
                                        mig_term=mig_term)
                meta.scores["normalized-score"] = preempt_scored[r]
                meta.norm_score = preempt_scored[r]
                continue
            if feasible_mask[r] and fit[r]:
                terms = [float(fitness[r]) / 18.0]
                meta = meta_for(node.id)
                meta.scores["binpack"] = terms[0]
                self._record_soft_terms(meta.scores, r, collisions,
                                        penalty, affinity_vec,
                                        spread_vec, has_affinities,
                                        has_spreads, desired,
                                        terms=terms, pol=pol,
                                        tput_term=tput_term,
                                        mig_term=mig_term)
                norm = sum(terms) / float(len(terms))
                meta.scores["normalized-score"] = norm
                meta.norm_score = norm
                continue
            if feasible_mask[r] and not fit[r]:
                # resource exhaustion: first dimension in the serial
                # superset order (structs.ComparableResources)
                if used_cpu[r] + ask_cpu > self.table.cpu_total[r]:
                    dim = "cpu"
                elif used_mem[r] + ask_mem > self.table.mem_total[r]:
                    dim = "memory"
                else:
                    dim = "disk"
                metrics.exhausted_node(node, dim)
                continue
            self._attribute_filter(
                node, r, tg, checks, csi_mask, dh_rows, dp_mask,
                dp_psets,
            )

    def _record_soft_terms(
        self, scores, r, collisions, penalty, affinity_vec,
        spread_vec, has_affinities, has_spreads, desired, terms,
        pol=None, tput_term=None, mig_term=None,
    ) -> None:
        """Record the rank chain's soft score components into one
        node's scores dict under the serial iterators' exact
        append/record conditions (rank.py: anti-affinity and
        reschedule-penalty record 0 when inert; affinity/spread
        record only non-zero).  Appends the *appended* terms to
        ``terms`` when given (the normalization mean divides by the
        append count, not the record count)."""
        coll = int(collisions[r])
        if coll > 0:
            anti = -1.0 * float(coll + 1) / desired
            if terms is not None:
                terms.append(anti)
            scores["job-anti-affinity"] = anti
        else:
            scores["job-anti-affinity"] = 0
        if penalty[r]:
            if terms is not None:
                terms.append(-1.0)
            scores["node-reschedule-penalty"] = -1
        else:
            scores["node-reschedule-penalty"] = 0
        if not has_affinities:
            scores["node-affinity"] = 0
        elif affinity_vec[r] != 0.0:
            aff = float(affinity_vec[r])
            if terms is not None:
                terms.append(aff)
            scores["node-affinity"] = aff
        if has_spreads and spread_vec[r] != 0.0:
            sp = float(spread_vec[r])
            if terms is not None:
                terms.append(sp)
            scores["allocation-spread"] = sp
        # policy components mirror rank.py PolicyIterator: throughput
        # records (and appends) for every node when the table is
        # present; migration appends only non-zero, records 0 when the
        # coefficient is armed but this node is not sticky
        if pol is not None:
            if tput_term is not None:
                tv = float(tput_term[r])
                if terms is not None:
                    terms.append(tv)
                scores["policy.throughput"] = tv
            mv = 0.0 if mig_term is None else float(mig_term[r])
            if mv != 0.0:
                if terms is not None:
                    terms.append(mv)
                scores["policy.migration"] = mv
            elif pol.mig_coef != 0.0:
                scores["policy.migration"] = 0

    def _explain_job_status(self, klass: str) -> int:
        """The wrapper's job-level class status, answered from the
        capture's SHADOW memoization (escape flags still come from
        the shared eligibility — they are pure job-spec facts)."""
        if self.ctx.eligibility.job_escaped or not klass:
            return CLASS_ESCAPED
        return self._explain_job_elig.get(klass, CLASS_UNKNOWN)

    def _explain_tg_status(self, tg_name: str, klass: str) -> int:
        if self.ctx.eligibility.tg_escaped.get(tg_name, False) or (
            not klass
        ):
            return CLASS_ESCAPED
        return self._explain_tg_elig.get(tg_name, {}).get(
            klass, CLASS_UNKNOWN
        )

    def _attribute_filter(
        self, node, row, tg, checks, csi_mask, dh_rows, dp_mask,
        dp_psets,
    ) -> None:
        """Name the reason a masked node was masked, walking the same
        checker order (and computed-class memoization) the serial
        FeasibilityWrapper would — the reason strings are the shared
        serial-chain vocabulary, never ad-hoc (lint-enforced by
        tools/check_stage_accounting.py).  Memoization runs on a
        shadow state private to the capture: the real EvalEligibility
        drives blocked-eval unblocking and must not change with the
        explain opt-out."""
        metrics = self.ctx.metrics
        klass = node.computed_class
        status = self._explain_job_status(klass)
        if status == CLASS_INELIGIBLE:
            metrics.filter_node(node, FILTER_CLASS_INELIGIBLE)
            return
        job_escaped = status == CLASS_ESCAPED
        job_unknown = status == CLASS_UNKNOWN
        for mask, label, level in checks:
            if level != "job":
                continue
            if not mask[row]:
                if not job_escaped:
                    self._explain_job_elig[klass] = CLASS_INELIGIBLE
                metrics.filter_node(node, label)
                return
        if not job_escaped and job_unknown:
            self._explain_job_elig[klass] = CLASS_ELIGIBLE
        status = self._explain_tg_status(tg.name, klass)
        if status == CLASS_INELIGIBLE:
            metrics.filter_node(node, FILTER_CLASS_INELIGIBLE)
            return
        if status != CLASS_ELIGIBLE:
            tg_escaped = status == CLASS_ESCAPED
            tg_unknown = status == CLASS_UNKNOWN
            for mask, label, level in checks:
                if level != "tg":
                    continue
                if not mask[row]:
                    if not tg_escaped:
                        self._explain_tg_elig.setdefault(
                            tg.name, {}
                        )[klass] = CLASS_INELIGIBLE
                    metrics.filter_node(node, label)
                    return
            if not tg_escaped and tg_unknown:
                self._explain_tg_elig.setdefault(tg.name, {})[
                    klass
                ] = CLASS_ELIGIBLE
        if csi_mask is not None and not csi_mask[row]:
            metrics.filter_node(node, FILTER_CONSTRAINT_CSI_VOLUMES)
            return
        if row in dh_rows:
            metrics.filter_node(node, CONSTRAINT_DISTINCT_HOSTS)
            return
        if dp_psets and not dp_mask[row]:
            for pset in dp_psets:
                ok, reason = pset.satisfies_distinct_properties(
                    node, tg.name
                )
                if not ok:
                    metrics.filter_node(node, reason)
                    return
            metrics.filter_node(node, CONSTRAINT_DISTINCT_PROPERTY)
            return
        # masked by a factor the serial source list never contains
        # (vacant arena row, node deactivated mid-snapshot): nothing
        # the serial chain would have named — leave unattributed

    # ------------------------------------------------------------------

    def _csi_feasibility(self, tg: TaskGroup) -> Optional[np.ndarray]:
        """Dynamic CSI mask (reference feasible.go:194): resolve each
        requested volume to its plugin column; a missing/unclaimable
        volume rules out every node.  Not cached — claims move with
        every plan apply."""
        reqs = [r for r in tg.volumes.values() if r.type == "csi"]
        if not reqs:
            return None
        out = np.ones(self.table.capacity, dtype=bool)
        for req in reqs:
            vol = self.ctx.state.csi_volume_by_id(
                self.job.namespace, req.source
            )
            if vol is None or not vol.claimable(req.read_only):
                out[:] = False
                return out
            col = self.table.column(f"csi.{vol.plugin_id}")
            out &= col.codes != -1
        return out

    def _static_checks(self, tg: TaskGroup):
        """Ordered ``(mask, label, level)`` triples in the serial
        FeasibilityWrapper's exact checker order (stack.py
        GenericStack: job constraints; then drivers, tg+task
        constraints, host volumes, devices, network), plus the
        combined AND with node eligibility folded in.  One structure
        feeds both the select's feasibility mask and the explain
        layer's per-node first-failure attribution, so the reason
        vocabulary can never drift from the serial path's."""
        key = (self.job.id, self.job.version, tg.name, self.table.generation)
        cached = self._static_mask_cache.get(key)
        if cached is not None:
            return cached
        C = self.table.capacity
        checks: List[Tuple[np.ndarray, str, str]] = []

        for constraint in self.job.constraints:
            m = self.compiler.constraint_mask(constraint)
            if m is not None:
                checks.append((m, str(constraint), "job"))

        constraints, drivers = task_group_constraints(tg)
        if drivers:
            driver_mask = np.ones(C, dtype=bool)
            for driver in drivers:
                col = self.table.column(f"driver.{driver}")
                driver_mask &= col.codes != -1
            checks.append(
                (driver_mask, FILTER_CONSTRAINT_DRIVERS, "tg")
            )
        for constraint in constraints:
            m = self.compiler.constraint_mask(constraint)
            if m is not None:
                checks.append((m, str(constraint), "tg"))
        for name, req in tg.volumes.items():
            if req.type == "host":
                col = self.table.column(f"hostvol.{req.source}")
                if req.read_only:
                    m = col.codes != -1
                else:
                    rw_code = col.interner.lookup("rw")
                    m = col.codes == rw_code
                checks.append(
                    (m, FILTER_CONSTRAINT_HOST_VOLUMES, "tg")
                )
            # csi is handled dynamically in select(): volume records
            # and claim capacity change without a table-generation bump
        device_reqs = [
            req for task in tg.tasks for req in task.resources.devices
        ]
        dev_mask = self.compiler.device_feasibility(device_reqs)
        if dev_mask is not None:
            checks.append((dev_mask, FILTER_CONSTRAINT_DEVICES, "tg"))
        if tg.networks:
            mode = tg.networks[0].mode or "host"
            if mode != "host":
                col = self.table.column(f"netmode.{mode}")
                checks.append(
                    (col.codes != -1, FILTER_CONSTRAINT_NETWORK, "tg")
                )

        combined = self.table.eligible.copy()
        for m, _label, _level in checks:
            combined &= m
        cached = (checks, combined)
        self._static_mask_cache[key] = cached
        return cached

    def _static_feasibility(self, tg: TaskGroup) -> np.ndarray:
        return self._static_checks(tg)[1]

    # ------------------------------------------------------------------

    def _plan_adjusted_state(self, tg: TaskGroup):
        """Proposed-alloc deltas relative to the store's live usage
        columns, plus job/job+tg proposed rows and collision counts
        (mirrors context.go:120 ProposedAllocs applied columnarly)."""
        C = self.table.capacity
        d_cpu = np.zeros(C, dtype=np.float64)
        d_mem = np.zeros(C, dtype=np.float64)
        d_disk = np.zeros(C, dtype=np.float64)
        collisions = np.zeros(C, dtype=np.int32)
        job_rows: Set[int] = set()
        job_tg_rows: Set[int] = set()

        plan = self.ctx.plan
        state = self.ctx.state
        removed_ids: Set[str] = set()

        for node_id, allocs in plan.node_update.items():
            row = self.table.row_of.get(node_id)
            for alloc in allocs:
                removed_ids.add(alloc.id)
                if row is None:
                    continue
                existing = state.alloc_by_id(alloc.id)
                if existing is not None and not existing.terminal_status():
                    res = existing.comparable_resources()
                    d_cpu[row] -= res.cpu
                    d_mem[row] -= res.memory_mb
                    d_disk[row] -= res.disk_mb
        for node_id, allocs in plan.node_preemptions.items():
            row = self.table.row_of.get(node_id)
            for alloc in allocs:
                removed_ids.add(alloc.id)
                if row is None:
                    continue
                existing = state.alloc_by_id(alloc.id)
                if existing is not None and not existing.terminal_status():
                    res = existing.comparable_resources()
                    d_cpu[row] -= res.cpu
                    d_mem[row] -= res.memory_mb
                    d_disk[row] -= res.disk_mb
        plan_alloc_ids: Set[str] = set()
        for node_id, allocs in plan.node_allocation.items():
            row = self.table.row_of.get(node_id)
            if row is None:
                continue
            for alloc in allocs:
                plan_alloc_ids.add(alloc.id)
                res = alloc.comparable_resources()
                d_cpu[row] += res.cpu
                d_mem[row] += res.memory_mb
                d_disk[row] += res.disk_mb
                existing = state.alloc_by_id(alloc.id)
                if (
                    existing is not None
                    and not existing.terminal_status()
                    and alloc.id not in removed_ids
                ):
                    # in-place replacement: the old version's usage is in
                    # the base columns; back it out
                    old = existing.comparable_resources()
                    d_cpu[row] -= old.cpu
                    d_mem[row] -= old.memory_mb
                    d_disk[row] -= old.disk_mb
                if alloc.job_id == self.job.id:
                    job_rows.add(row)
                    if alloc.task_group == tg.name:
                        job_tg_rows.add(row)
                        collisions[row] += 1

        # existing state allocs of this job
        for alloc in state.allocs_by_job(
            self.job.namespace, self.job.id
        ):
            if alloc.terminal_status():
                continue
            if alloc.id in removed_ids or alloc.id in plan_alloc_ids:
                continue
            row = self.table.row_of.get(alloc.node_id)
            if row is None:
                continue
            job_rows.add(row)
            if alloc.task_group == tg.name:
                job_tg_rows.add(row)
                collisions[row] += 1
        return d_cpu, d_mem, d_disk, collisions, job_rows, job_tg_rows

    # ------------------------------------------------------------------

    def _affinity_vector(self, tg: TaskGroup) -> np.ndarray:
        key = (tg.name, self.table.generation)
        cached = self._affinity_cache.get(key)
        if cached is None:
            affinities = (
                list(self.job.affinities)
                + list(tg.affinities)
                + [a for t in tg.tasks for a in t.affinities]
            )
            total, sum_weight = self.compiler.affinity_score_vector(
                affinities
            )
            vec = (
                total / sum_weight
                if sum_weight
                else np.zeros(self.table.capacity)
            )
            cached = (vec, sum_weight)
            self._affinity_cache[key] = cached
        return cached[0]

    # ------------------------------------------------------------------

    def _spread_vector(self, tg: TaskGroup) -> Tuple[np.ndarray, bool]:
        """Total spread boost per node (spread.py semantics, vectorized
        per select because use counts track the accumulating plan)."""
        C = self.table.capacity
        combined = list(tg.spreads) + list(self.job.spreads)
        if not combined:
            return np.zeros(C, dtype=np.float64), False

        if tg.name not in self._spread_psets:
            psets = []
            # job-level spreads first, then tg-level (spread.go:79-92)
            for spread in list(self.job.spreads) + list(tg.spreads):
                pset = PropertySet(self.ctx, self.job)
                pset.set_target_attribute(spread.attribute, tg.name)
                psets.append(pset)
            self._spread_psets[tg.name] = psets
            from .spread import compute_spread_info

            info, sum_weights = compute_spread_info(combined, tg.count)
            self._spread_info[tg.name] = info
            self._sum_spread_weights = sum_weights
        else:
            for pset in self._spread_psets[tg.name]:
                pset.populate_proposed()

        total = np.zeros(C, dtype=np.float64)
        info = self._spread_info[tg.name]
        for pset in self._spread_psets[tg.name]:
            attr_info = info.get(pset.target_attribute)
            if attr_info is None:
                continue
            desired_counts = attr_info["desired_counts"]
            combined_use = pset.get_combined_use_map()
            if desired_counts:
                weight_frac = float(attr_info["weight"]) / float(
                    self._sum_spread_weights
                )
                total += self.compiler.spread_boost_vector(
                    pset.target_attribute,
                    weight_frac,
                    desired_counts,
                    combined_use,
                )
            else:
                total += self.compiler.spread_boost_vector(
                    pset.target_attribute, None, None, combined_use
                )
        return total, True

    # ------------------------------------------------------------------

    def _distinct_property_state(
        self, tg: TaskGroup
    ) -> Tuple[np.ndarray, List[PropertySet]]:
        """Distinct-property feasibility mask plus the property sets
        behind it — the mask drives the kernel; the psets let the
        explain capture render the exact per-node reason string the
        serial chain would (propertyset.py
        satisfies_distinct_properties)."""
        C = self.table.capacity
        mask = np.ones(C, dtype=bool)
        constraints = [
            (c, "")
            for c in self.job.constraints
            if c.operand == CONSTRAINT_DISTINCT_PROPERTY
        ] + [
            (c, tg.name)
            for c in tg.constraints
            if c.operand == CONSTRAINT_DISTINCT_PROPERTY
        ]
        if not constraints:
            return mask, []
        from .feasible import target_column_key

        psets: List[PropertySet] = []
        for constraint, scope in constraints:
            pset = PropertySet(self.ctx, self.job)
            pset.set_constraint(constraint, scope)
            psets.append(pset)
            key = target_column_key(constraint.ltarget)
            if not key:
                continue
            col = self.table.column(key)
            combined = pset.get_combined_use_map()
            allowed = pset.allowed_count
            lut = np.ones(len(col.interner.values) + 1, dtype=bool)
            for i, value in enumerate(col.interner.values):
                lut[i] = combined.get(value, 0) < allowed
            lut[-1] = False  # missing property fails
            mask &= lut[col.codes]
        return mask, psets

    def _distinct_property_mask(self, tg: TaskGroup) -> np.ndarray:
        return self._distinct_property_state(tg)[0]

    # ------------------------------------------------------------------

    def _populate_class_eligibility(
        self, tg: TaskGroup, static_mask: np.ndarray
    ) -> None:
        """After a failed placement, record which computed classes passed
        the feasibility layer so blocked evals unblock correctly
        (context.go:190 EvalEligibility; mask-derived here)."""
        elig = self.ctx.eligibility
        col = self.table.column("node.computed_class")
        candidate_mask = np.zeros(self.table.capacity, dtype=bool)
        candidate_mask[self.candidate_rows] = True
        active = candidate_mask & self.table.active & self.table.eligible
        for code, klass in enumerate(col.interner.values):
            rows = (col.codes == code) & active
            if not rows.any():
                continue
            ok = bool((rows & static_mask).any())
            if not elig.job_escaped:
                elig.set_job_eligibility(ok, klass)
            if not elig.tg_escaped.get(tg.name, False):
                elig.set_task_group_eligibility(ok, tg.name, klass)


class TPUSystemStack:
    """Vectorized system stack (reference stack.go:182-318 SystemStack,
    system_sched.go:54).

    The system scheduler scores *every* feasible node for the job — no
    visit limit — which makes the feasibility chain the dominant cost
    at fleet scale: the oracle walks every node through every checker.
    Here the whole constraint surface compiles ONCE per (job, task
    group, table generation) into columnar masks with first-failure
    attribution (the order FeasibilityWrapper runs its checkers), so a
    select on node n is a mask lookup; only *placed* nodes run the
    exact single-node binpack chain (ports, devices, preemption,
    AllocsFit, scoring — rank.go:188), host-side, exactly as the
    reference does per visited node.

    Known metric-string divergence (placements identical): the oracle
    attributes nodes of a memoized-ineligible computed class to
    "computed class ineligible" after the first; the mask path always
    names the concrete failing constraint.
    """

    def __init__(self, ctx: EvalContext, seed=None) -> None:
        from .rank import (
            PreemptionScoringIterator,
            ScoreNormalizationIterator,
        )

        self.ctx = ctx
        self.table = ctx.state.node_table
        self.compiler = MaskCompiler(self.table)
        self.job: Optional[Job] = None
        self.node: Optional[Node] = None
        # (job.version, tg.name, generation) -> list[(mask, attribution)]
        # in FeasibilityWrapper checker order + the combined mask
        self._mask_cache: Dict[Tuple, Tuple] = {}
        self._pset_cache: Dict[str, List] = {}
        self._elig_done: Set[Tuple] = set()
        # the exact per-node chain tail, built once and re-fed per
        # select exactly as the oracle SystemStack reuses its iterators
        config = ctx.state.scheduler_config()
        self._source = _SingleNodeSource(None)
        self._binpack = BinPackIterator(
            ctx,
            self._source,
            config.preemption_config.system_scheduler_enabled,
            0,
            config.effective_scheduler_algorithm(),
        )
        scorer = PreemptionScoringIterator(ctx, self._binpack)
        self._norm = ScoreNormalizationIterator(ctx, scorer)

    # ------------------------------------------------------------------

    def set_nodes(self, base_nodes: List[Node]) -> None:
        # the system scheduler feeds one node per select
        # (system_sched.go computePlacements); only that node is kept
        self.node = base_nodes[0] if base_nodes else None

    def set_job(self, job: Job) -> None:
        if self.job is not None and self.job.version == job.version:
            return
        self.job = job
        self.ctx.eligibility.set_job(job)
        self._binpack.set_job(job)
        self._mask_cache.clear()
        self._pset_cache.clear()
        self._elig_done.clear()

    # ------------------------------------------------------------------

    def _checks(self, tg: TaskGroup):
        """Ordered (mask, attribution) pairs mirroring the wrapper's
        checker order (feasible.go FeasibilityWrapper: job constraints;
        drivers, tg constraints, host volumes, devices, network; CSI),
        plus the combined AND of all masks."""
        key = (self.job.version, tg.name, self.table.generation)
        cached = self._mask_cache.get(key)
        if cached is not None:
            return cached
        from .feasible import (
            FILTER_CONSTRAINT_DEVICES,
            FILTER_CONSTRAINT_DRIVERS,
            FILTER_CONSTRAINT_HOST_VOLUMES,
        )

        C = self.table.capacity
        checks: List[Tuple[np.ndarray, str]] = []

        for constraint in self.job.constraints:
            m = self.compiler.constraint_mask(constraint)
            if m is not None:
                checks.append((m, str(constraint)))
        constraints, drivers = task_group_constraints(tg)
        driver_mask = np.ones(C, dtype=bool)
        for driver in drivers:
            col = self.table.column(f"driver.{driver}")
            driver_mask &= col.codes != -1
        checks.append((driver_mask, FILTER_CONSTRAINT_DRIVERS))
        for constraint in constraints:
            m = self.compiler.constraint_mask(constraint)
            if m is not None:
                checks.append((m, str(constraint)))
        for name, req in tg.volumes.items():
            if req.type != "host":
                continue
            col = self.table.column(f"hostvol.{req.source}")
            if req.read_only:
                m = col.codes != -1
            else:
                rw_code = col.interner.lookup("rw")
                m = col.codes == rw_code
            checks.append((m, FILTER_CONSTRAINT_HOST_VOLUMES))
        device_reqs = [
            req for task in tg.tasks for req in task.resources.devices
        ]
        dev_mask = self.compiler.device_feasibility(device_reqs)
        if dev_mask is not None:
            checks.append((dev_mask, FILTER_CONSTRAINT_DEVICES))
        if tg.networks:
            mode = tg.networks[0].mode or "host"
            if mode != "host":
                col = self.table.column(f"netmode.{mode}")
                checks.append(
                    (col.codes != -1, FILTER_CONSTRAINT_NETWORK)
                )

        combined = np.ones(C, dtype=bool)
        for m, _label in checks:
            combined &= m
        cached = (checks, combined)
        self._mask_cache[key] = cached
        return cached

    def _csi_check(self, tg: TaskGroup) -> Optional[Tuple[np.ndarray, str]]:
        reqs = [r for r in tg.volumes.values() if r.type == "csi"]
        if not reqs:
            return None
        out = np.ones(self.table.capacity, dtype=bool)
        for req in reqs:
            vol = self.ctx.state.csi_volume_by_id(
                self.job.namespace, req.source
            )
            if vol is None or not vol.claimable(req.read_only):
                out[:] = False
                break
            col = self.table.column(f"csi.{vol.plugin_id}")
            out &= col.codes != -1
        return out, "missing CSI plugins"

    def _distinct_property_psets(self, tg: TaskGroup) -> List:
        psets = self._pset_cache.get(tg.name)
        if psets is None:
            psets = []
            for c in self.job.constraints:
                if c.operand == CONSTRAINT_DISTINCT_PROPERTY:
                    pset = PropertySet(self.ctx, self.job)
                    pset.set_constraint(c, "")
                    psets.append(pset)
            for c in tg.constraints:
                if c.operand == CONSTRAINT_DISTINCT_PROPERTY:
                    pset = PropertySet(self.ctx, self.job)
                    pset.set_constraint(c, tg.name)
                    psets.append(pset)
            self._pset_cache[tg.name] = psets
        else:
            for pset in psets:
                pset.populate_proposed()
        return psets

    def _populate_eligibility(
        self, tg: TaskGroup, combined: np.ndarray
    ) -> None:
        """Class eligibility for blocked-eval unblocking, derived from
        the masks (context.go:190)."""
        key = (self.job.version, tg.name, self.table.generation)
        if key in self._elig_done:
            return
        self._elig_done.add(key)
        elig = self.ctx.eligibility
        col = self.table.column("node.computed_class")
        active = self.table.active
        for code, klass in enumerate(col.interner.values):
            rows = (col.codes == code) & active
            if not rows.any():
                continue
            ok = bool((rows & combined).any())
            if not elig.job_escaped:
                elig.set_job_eligibility(ok, klass)
            if not elig.tg_escaped.get(tg.name, False):
                elig.set_task_group_eligibility(ok, tg.name, klass)

    # ------------------------------------------------------------------

    def select(
        self, tg: TaskGroup, options: Optional[SelectOptions] = None
    ) -> Optional[RankedNode]:
        self.ctx.reset()
        node = self.node
        if node is None:
            return None
        row = self.table.row_of.get(node.id)
        if row is None:
            return None
        metrics = self.ctx.metrics
        metrics.evaluate_node()

        checks, combined = self._checks(tg)
        self._populate_eligibility(tg, combined)
        if not combined[row]:
            for mask, label in checks:
                if not mask[row]:
                    metrics.filter_node(node, label)
                    return None
        csi = self._csi_check(tg)
        if csi is not None and not csi[0][row]:
            metrics.filter_node(node, csi[1])
            return None
        for pset in self._distinct_property_psets(tg):
            ok, reason = pset.satisfies_distinct_properties(
                node, tg.name
            )
            if not ok:
                metrics.filter_node(node, reason)
                return None

        # exact per-node placement: ports/devices/preemption/fit +
        # scoring through the oracle chain tail (binpack -> preemption
        # scoring -> normalization), identical to SystemStack
        self._source.ranked = RankedNode(node=node)
        self._source.done = False
        self._binpack.set_task_group(tg)
        return self._norm.next()
