"""Storm coalescing: stage a family backlog as ONE assignment
problem, and decompose the converged solve back into per-eval
prescored plans.

The batch worker detects a storm (a contiguous broker prefix of
pending evals sharing a job family — see eval_broker.job_family) and,
instead of feeding them through the per-eval chunk chain, hands them
here.  ``build_storm_problem`` runs the same host staging the chunk
assembler uses — simulation pre-pass output, candidate layout, static
feasibility/affinity masks (ops/constraints.py), the recorded serial
walk order — but flattens every pending placement of every member
into one (alloc-rows x node-arena) matrix for ``ops/solve.py``.

``decompose`` maps the solved assignment back to each eval's
``(rows, pulls)`` pick list, which then replays through the EXISTING
prescored machinery: GenericScheduler + PrescoredStack exact winner
verification, speculative wave + ``_commit_wave`` conflict fences, in
broker FIFO order.  Members the solver cannot cover — ineligible
shape, failed simulation, or an unassignable row — keep
``rows=None`` and fall back to the serial chain inside the same
in-order commit, so zero evals are ever lost and correctness never
depends on the solver.

Eligibility is deliberately narrow (single task group, no ports /
devices / distinct constraints / spreads / staged evictions): the
solver's capacity model covers cpu/mem/disk only, and everything it
does not model must go down the exact path, not be approximated.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
)
from .stack import compute_visit_limit

# one solve covers at most this many pending-alloc rows; members past
# the budget keep rows=None and re-enter the normal batch path
MAX_STORM_ROWS = 1024
_INT32_MAX = 2**31 - 1


@dataclass
class StormMember:
    """One storm eval's journey through the solve: gate reason (None =
    solvable), its row slice in the flattened problem, and the
    decomposed per-pick plan."""

    ev: object
    token: str
    job: object = None
    sim: object = None
    reason: Optional[str] = None  # non-None = serial-chain fallback
    row0: int = 0
    row1: int = 0
    # filled by decompose() for solved members
    rows: Optional[List[int]] = None
    pulls: Optional[List[int]] = None
    solver_round: int = -1
    assignment_score: float = 0.0
    divergent_rows: int = 0
    # leadership generation the storm solved under — stamped by the
    # batch worker and carried into the member's Storm explain block,
    # so a post-failover audit can tell which leader's solve placed it
    leader_gen: int = 0


@dataclass
class StormProblem:
    """Flattened (rows x nodes) assignment problem + row bookkeeping."""

    inputs: object  # ops.solve.StormInputs (numpy leaves)
    members: List[StormMember] = field(default_factory=list)
    n_rows: int = 0  # real rows (before padding)
    n_evals: int = 0  # solvable members contributing rows
    spread_fit: bool = False
    max_rounds: int = 1


def storm_gate(worker, member: StormMember) -> Optional[str]:
    """Why this member cannot ride the solver (None = it can).  The
    vocabulary mirrors the admission gates: every reason is a trace
    event and a fallback counter, never a dropped eval."""
    job, sim = member.job, member.sim
    if sim is None:
        return member.reason or "simulate"
    if len(sim.tgs) > 1:
        return "multi_tg"
    if any(sim.asked_ports):
        return "ports"
    if any(d for d in sim.asked_devices):
        return "devices"
    if any(r >= 0 for r in sim.evict_rows):
        # destructive evictions interleave with placements in the
        # serial chain; the solver's flat capacity model cannot
        return "evictions"
    tg = sim.tgs[0] if sim.tgs else job.task_groups[0]
    for c in (
        list(job.constraints)
        + list(tg.constraints)
        + [c for t in tg.tasks for c in t.constraints]
    ):
        if c.operand in (
            CONSTRAINT_DISTINCT_HOSTS,
            CONSTRAINT_DISTINCT_PROPERTY,
        ):
            # distinct placement is a hard constraint the flat score
            # matrix does not encode — a co-assignment would violate
            # it invisibly to the exact winner verification
            return "distinct"
    if list(job.spreads) or list(tg.spreads):
        # spread boosts evolve per pick through the chain carry; the
        # solver scores once against the baseline
        return "spread"
    return None


def build_storm_problem(
    worker, snap, members: List[StormMember]
) -> Optional[StormProblem]:
    """Stage the solvable members' pending placements into one
    ``StormInputs``.  Returns None when no member is solvable (the
    caller routes the whole storm through the normal batch path).
    Mutates each member's ``reason``/row slice in place."""
    from ..ops.batch import pow2_bucket
    from ..ops.solve import StormInputs, pad_axis
    from ..raft import chaos as _chaos
    from ..trace import TRACE
    from .policy import (
        migration_vector,
        resolve,
        sticky_node_ids,
        tput_tensor,
    )

    # chaos seam: deterministic revoke-while-staging races (no-op
    # unless a test armed the hook)
    _chaos.fire("storm_staged")

    table = snap.node_table
    C = table.capacity
    dtype = np.asarray(table.cpu_total).dtype

    feas_e: List[np.ndarray] = []
    aff_e: List[np.ndarray] = []
    coll_e: List[np.ndarray] = []
    perm_e: List[np.ndarray] = []
    limit_e: List[int] = []
    ncand_e: List[int] = []
    # policy-weighted rows (sched/policy.py): PRE-SCALED term rows
    # (ops/score.py PolicyTerms) staged per eval so a mixed storm
    # fuses weighted and unweighted members into ONE solve —
    # policy-less evals carry all-zero rows, which add float-exactly
    # nothing
    pol_tput_e: List[np.ndarray] = []
    pol_has_e: List[float] = []
    pol_mig_e: List[np.ndarray] = []
    any_policy = False
    metrics = getattr(getattr(worker, "server", None), "metrics", None)
    eval_of: List[int] = []
    ask_rows: List[Tuple[float, float, float]] = []
    desired_rows: List[int] = []
    penalty_rows: List[np.ndarray] = []
    pre: Dict[int, List[float]] = {}

    n_evals = 0
    n_rows = 0
    for member in members:
        if member.reason is None:
            member.reason = storm_gate(worker, member)
        if member.reason is None and (
            n_rows + member.sim.placements > MAX_STORM_ROWS
        ):
            member.reason = "row_budget"
        if member.reason is not None:
            continue
        ev, job, sim = member.ev, member.job, member.sim
        tg = sim.tgs[0] if sim.tgs else job.task_groups[0]
        # SHARED walk-order staging (candidates, recorded serial
        # shuffle, perm, replay passthrough mirror) — the same
        # helper `_assemble` runs, so a solved member replays
        # through the identical PrescoredStack contract and the
        # degenerate-parity guarantee can't drift
        rows, _rest, n_cand, _order, perm = (
            worker._stage_walk_order(snap, job, sim)
        )
        perm = perm.astype(np.int32)
        feasible, aff_vec = worker._static_vectors(
            snap, job, tg, rows
        )
        has_aff = bool(
            list(job.affinities)
            or list(tg.affinities)
            or any(t.affinities for t in tg.tasks)
        )
        pol = resolve(job)
        if pol is not None:
            # same assembly the single-eval vectorized select runs:
            # cached throughput tensor + live-alloc stickiness vector,
            # all from replicated state (followers stage identically),
            # pre-scaled by the coefficients here so the kernel adds
            # the rows as-is (host f64 muls are bit-identical to the
            # device muls they replace)
            with TRACE.span(ev.id, "batch_worker.policy_assemble"):
                tput_term = (
                    pol.tput_coef
                    * tput_tensor(
                        pol, job, table, dtype=dtype, metrics=metrics
                    )
                    if pol.has_tput
                    else np.zeros(C, dtype=dtype)
                )
                sticky = sticky_node_ids(pol, job, tg.name, snap)
                mig_term = (
                    pol.mig_coef
                    * migration_vector(sticky, table, dtype=dtype)
                    if sticky
                    else np.zeros(C, dtype=dtype)
                )
            any_policy = True
            if metrics is not None:
                metrics.incr("policy.storm_evals")
            pol_tput_e.append(tput_term)
            pol_has_e.append(1.0 if pol.has_tput else 0.0)
            pol_mig_e.append(mig_term)
        else:
            pol_tput_e.append(np.zeros(C, dtype=dtype))
            pol_has_e.append(0.0)
            pol_mig_e.append(np.zeros(C, dtype=dtype))
        limit = (
            _INT32_MAX
            # weighted scoring joins affinity in the unlimited-walk
            # rule (stack.py select): the survey must cover every
            # candidate or the kernel/oracle walks diverge
            if has_aff or pol is not None
            else compute_visit_limit(n_cand, ev.type == "batch")
        )
        e_i = n_evals
        feas_e.append(feasible.astype(bool))
        aff_e.append(np.asarray(aff_vec, dtype=dtype))
        coll = (
            sim.base_collisions[0]
            if sim.base_collisions is not None
            else np.zeros(C, dtype=np.int32)
        )
        coll_e.append(coll.astype(np.int32))
        perm_e.append(perm)
        limit_e.append(int(limit))
        ncand_e.append(int(n_cand))
        ask = (
            float(sum(t.resources.cpu for t in tg.tasks)),
            float(sum(t.resources.memory_mb for t in tg.tasks)),
            float(tg.ephemeral_disk.size_mb),
        )
        member.row0 = n_rows
        for pick in range(sim.placements):
            eval_of.append(e_i)
            ask_rows.append(ask)
            desired_rows.append(int(tg.count))
            pen = np.zeros(C, dtype=bool)
            if pick < len(sim.penalties):
                for node_id in sim.penalties[pick]:
                    row = table.row_of.get(node_id)
                    if row is not None:
                        pen[row] = True
            penalty_rows.append(pen)
            n_rows += 1
        member.row1 = n_rows
        n_evals += 1
        # staged pre-placement deltas (stops, in-place updates) of
        # every solvable member apply up front: the solver sees the
        # storm's own freed/shifted capacity like the chain carry
        # would, one snapshot earlier (audited divergence)
        for row, delta in sim.pre.items():
            acc = pre.setdefault(row, [0.0, 0.0, 0.0])
            acc[0] += delta[0]
            acc[1] += delta[1]
            acc[2] += delta[2]

    if n_evals == 0:
        return None

    E = pow2_bucket(max(1, n_evals), floor=4)
    A = pow2_bucket(max(1, n_rows), floor=8)
    pre_cpu = np.zeros(C, dtype=dtype)
    pre_mem = np.zeros(C, dtype=dtype)
    pre_disk = np.zeros(C, dtype=dtype)
    for row, delta in pre.items():
        pre_cpu[row] = delta[0]
        pre_mem[row] = delta[1]
        pre_disk[row] = delta[2]

    inputs = StormInputs(
        feasible=pad_axis(
            np.stack(feas_e) if feas_e
            else np.zeros((1, C), dtype=bool),
            E, False,
        ),
        affinity=pad_axis(
            np.stack(aff_e) if aff_e
            else np.zeros((1, C), dtype=dtype),
            E, 0,
        ),
        collisions=pad_axis(
            np.stack(coll_e) if coll_e
            else np.zeros((1, C), dtype=np.int32),
            E, 0,
        ),
        perm=pad_axis(
            np.stack(perm_e) if perm_e
            else np.arange(C, dtype=np.int32)[None, :],
            E, 0,
        ),
        limit=pad_axis(
            np.asarray(limit_e or [1], dtype=np.int32), E, 1
        ),
        n_cand=pad_axis(
            np.asarray(ncand_e or [1], dtype=np.int32), E, 1
        ),
        eval_of=pad_axis(
            np.asarray(eval_of or [0], dtype=np.int32), A, 0
        ),
        penalty=pad_axis(
            np.stack(penalty_rows) if penalty_rows
            else np.zeros((1, C), dtype=bool),
            A, False,
        ),
        ask=pad_axis(
            np.asarray(
                ask_rows or [(0.0, 0.0, 0.0)], dtype=dtype
            ),
            A, 0,
        ),
        desired=pad_axis(
            np.asarray(desired_rows or [1], dtype=np.int32), A, 1
        ),
        real=pad_axis(np.ones(n_rows, dtype=bool), A, False)
        if n_rows
        else np.zeros(A, dtype=bool),
        pre_cpu=pre_cpu,
        pre_mem=pre_mem,
        pre_disk=pre_disk,
        # None (not zeros) when no member carries a policy: absent
        # pytree leaves keep the unweighted solve's compiled
        # signature, so policy-off storms trace bit-identically
        policy_tput_term=pad_axis(np.stack(pol_tput_e), E, 0)
        if any_policy
        else None,
        policy_has_tput=pad_axis(
            np.asarray(pol_has_e, dtype=dtype), E, 0
        )
        if any_policy
        else None,
        policy_mig_term=pad_axis(np.stack(pol_mig_e), E, 0)
        if any_policy
        else None,
    )
    spread_fit = (
        snap.scheduler_config().effective_scheduler_algorithm()
        == "spread"
    )
    return StormProblem(
        inputs=inputs,
        members=members,
        n_rows=n_rows,
        n_evals=n_evals,
        spread_fit=spread_fit,
        max_rounds=A,
    )


def stage_for_mesh(inputs, mesh):
    """Commit one storm's staged ``StormInputs`` onto the node-axis
    mesh for the SHARDED solve (`ops/solve.py
    storm_assignment_sharded`): node-indexed leaves land sharded
    ``P("nodes")`` — on a multi-host mesh each process ships ONLY its
    own shards' slices of the [E, C]/[A, C] masks and the pre-
    placement columns, so staging a pod-wide storm costs every host
    O(rows x C/hosts) bytes, not the full problem — and per-eval /
    per-row leaves replicate onto local devices.  The arena capacity
    must tile evenly over the mesh (the caller's gate; same condition
    as ``mesh_capable``)."""
    from ..ops.solve import StormInputs, storm_in_specs
    from ..parallel.mesh import mesh_put

    weighted = inputs.policy_tput_term is not None
    return StormInputs(
        *(
            None
            if leaf is None
            else mesh_put(mesh, np.asarray(leaf), spec)
            for leaf, spec in zip(inputs, storm_in_specs(weighted))
        )
    )


def decompose(problem: StormProblem, out) -> int:
    """Map the converged assignment back onto the members: fill each
    solved member's ``(rows, pulls)`` pick lists (broker FIFO order is
    the member order — the commit wave preserves it), tag it with the
    solver round and assignment score for the explain ring, and mark
    members with any unassigned row as ``unsolved`` fallbacks.
    Returns the number of assigned rows.

    ``out=None`` (the solve never ran: a zero-row storm, or a launch
    failure) solves only the trivial members — zero-placement evals
    commit with an empty pick list; everything else falls back."""
    solved_rows = 0
    if out is None:
        for member in problem.members:
            if member.reason is not None:
                continue
            if member.row0 == member.row1:
                member.rows = []
                member.pulls = []
                member.solver_round = 0
            else:
                member.reason = "unsolved"
        return 0
    assigned, pulls, acc_round, score, greedy, _rounds = out
    for member in problem.members:
        if member.reason is not None:
            continue
        r0, r1 = member.row0, member.row1
        rows = [int(r) for r in assigned[r0:r1]]
        if any(r < 0 for r in rows):
            # an unassignable row (nothing feasible fits, or the
            # round budget ran out): the SERIAL chain owns this eval
            # — a solver "no node" must never masquerade as the
            # scheduler's exhaustion verdict
            member.reason = "unsolved"
            continue
        member.rows = rows
        member.pulls = [int(p) for p in pulls[r0:r1]]
        member.solver_round = int(
            max([int(r) for r in acc_round[r0:r1]], default=-1)
        )
        member.assignment_score = float(np.sum(score[r0:r1]))
        member.divergent_rows = int(
            np.sum(assigned[r0:r1] != greedy[r0:r1])
        )
        solved_rows += r1 - r0
    return solved_rows
