"""Property usage counting for distinct_property and spread
(reference scheduler/propertyset.go).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple, TYPE_CHECKING

from ..structs import Allocation, Constraint, Job, Node

if TYPE_CHECKING:  # pragma: no cover
    from .context import EvalContext


def get_property(node: Optional[Node], prop: str) -> Tuple[str, bool]:
    """(reference propertyset.go:getProperty)"""
    from .feasible import resolve_target

    if node is None or not prop:
        return "", False
    val, ok = resolve_target(prop, node)
    if not ok or not isinstance(val, str):
        return "", False
    return val, True


class PropertySet:
    def __init__(self, ctx: "EvalContext", job: Job) -> None:
        self.ctx = ctx
        self.job_id = job.id
        self.namespace = job.namespace
        self.task_group = ""
        self.target_attribute = ""
        self.allowed_count = 0
        self.error_building: Optional[str] = None
        self.existing_values: Dict[str, int] = {}
        self.proposed_values: Dict[str, int] = {}
        self.cleared_values: Dict[str, int] = {}

    # -- configuration ---------------------------------------------------

    def set_constraint(self, constraint: Constraint, task_group: str) -> None:
        """distinct_property: RTarget is the allowed count (default 1)
        (reference propertyset.go:setConstraint)."""
        if constraint.rtarget:
            try:
                allowed = int(constraint.rtarget)
            except ValueError:
                self.error_building = (
                    f"failed to convert RTarget {constraint.rtarget!r} to int"
                )
                return
        else:
            allowed = 1
        self._set_target(constraint.ltarget, allowed, task_group)

    def set_target_attribute(self, attribute: str, task_group: str) -> None:
        """Spread parameterization: no allowed count."""
        self._set_target(attribute, 0, task_group)

    def _set_target(self, attribute: str, allowed: int, task_group: str) -> None:
        if task_group:
            self.task_group = task_group
        self.target_attribute = attribute
        self.allowed_count = allowed
        self._populate_existing()
        self.populate_proposed()

    # -- population ------------------------------------------------------

    def _populate_existing(self) -> None:
        allocs = self.ctx.state.allocs_by_job(self.namespace, self.job_id)
        allocs = self._filter(allocs, filter_terminal=True)
        self._count(allocs, self.existing_values)

    def populate_proposed(self) -> None:
        """(reference propertyset.go:PopulateProposed)"""
        self.proposed_values = {}
        self.cleared_values = {}

        stopping: List[Allocation] = []
        for updates in self.ctx.plan.node_update.values():
            stopping.extend(updates)
        stopping = self._filter(stopping, filter_terminal=False)

        proposed: List[Allocation] = []
        for placements in self.ctx.plan.node_allocation.values():
            proposed.extend(placements)
        proposed = self._filter(proposed, filter_terminal=True)

        self._count(stopping, self.cleared_values)
        self._count(proposed, self.proposed_values)

        for value in list(self.proposed_values):
            current = self.cleared_values.get(value)
            if current is None:
                continue
            if current == 0:
                del self.cleared_values[value]
            elif current > 1:
                self.cleared_values[value] = current - 1

    # -- queries ---------------------------------------------------------

    def satisfies_distinct_properties(
        self, option: Node, tg: str
    ) -> Tuple[bool, str]:
        nvalue, error_msg, used = self.used_count(option, tg)
        if error_msg:
            return False, error_msg
        if used < self.allowed_count:
            return True, ""
        return (
            False,
            f"distinct_property: {self.target_attribute}={nvalue} "
            f"used by {used} allocs",
        )

    def used_count(self, option: Node, tg: str) -> Tuple[str, str, int]:
        if self.error_building:
            return "", self.error_building, 0
        nvalue, ok = get_property(option, self.target_attribute)
        if not ok:
            return (
                nvalue,
                f'missing property "{self.target_attribute}"',
                0,
            )
        combined = self.get_combined_use_map()
        return nvalue, "", combined.get(nvalue, 0)

    def get_combined_use_map(self) -> Dict[str, int]:
        combined: Dict[str, int] = {}
        for values in (self.existing_values, self.proposed_values):
            for value, count in values.items():
                combined[value] = combined.get(value, 0) + count
        for value, cleared in self.cleared_values.items():
            if value not in combined:
                continue
            combined[value] = max(0, combined[value] - cleared)
        return combined

    # -- helpers ---------------------------------------------------------

    def _filter(
        self, allocs: List[Allocation], filter_terminal: bool
    ) -> List[Allocation]:
        out = []
        for alloc in allocs:
            if filter_terminal and alloc.terminal_status():
                continue
            if self.task_group and alloc.task_group != self.task_group:
                continue
            out.append(alloc)
        return out

    def _count(
        self, allocs: List[Allocation], into: Dict[str, int]
    ) -> None:
        for value, n in count_values_by_property(
            self.ctx.state, self.target_attribute, allocs
        ).items():
            into[value] = into.get(value, 0) + n


def count_values_by_property(
    state, attribute: str, allocs: List[Allocation]
) -> Dict[str, int]:
    """Allocs per value of their node's property (reference
    propertyset.go _count) — the single counting implementation shared
    by PropertySet and the batch worker's in-kernel spread inputs."""
    out: Dict[str, int] = {}
    for alloc in allocs:
        node = state.node_by_id(alloc.node_id)
        value, ok = get_property(node, attribute)
        if not ok:
            continue
        out[value] = out.get(value, 0) + 1
    return out
