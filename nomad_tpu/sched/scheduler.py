"""Scheduler registry and interfaces (reference scheduler/scheduler.go).

`BUILTIN_SCHEDULERS` maps eval type -> factory (scheduler.go:23); the TPU
backend is not a separate type here — both the generic and system
schedulers take a ``use_tpu`` flag (driven by
`SchedulerConfiguration.tpu_scheduler_enabled`) selecting between the
oracle stack and the vectorized stack, mirroring how the reference selects
binpack/spread via runtime scheduler config (stack.go:382).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Protocol, TYPE_CHECKING

from ..structs import Evaluation, Plan, PlanResult

if TYPE_CHECKING:  # pragma: no cover
    from ..state.store import StateSnapshot

SCHEDULER_VERSION = 1


class SchedulerError(Exception):
    pass


class SetStatusError(SchedulerError):
    """Raised when a scheduler fails and the eval should be marked failed
    (reference scheduler.go SetStatusError)."""

    def __init__(self, err: str, eval_status: str) -> None:
        super().__init__(err)
        self.eval_status = eval_status


class Planner(Protocol):
    """The scheduler's only write path
    (reference scheduler/scheduler.go:112)."""

    def submit_plan(self, plan: Plan) -> "tuple[PlanResult, StateSnapshot]":
        ...

    def update_eval(self, evaluation: Evaluation) -> None:
        ...

    def create_eval(self, evaluation: Evaluation) -> None:
        ...

    def reblock_eval(self, evaluation: Evaluation) -> None:
        ...


BUILTIN_SCHEDULERS: Dict[str, Callable] = {}


def register_scheduler(name: str, factory: Callable) -> None:
    BUILTIN_SCHEDULERS[name] = factory


def new_scheduler(
    name: str,
    state: "StateSnapshot",
    planner: Planner,
    **kwargs,
):
    factory = BUILTIN_SCHEDULERS.get(name)
    if factory is None:
        raise SchedulerError(f"unknown scheduler {name!r}")
    return factory(state, planner, **kwargs)


def _register_builtins() -> None:
    from .generic_sched import BatchScheduler, ServiceScheduler
    from .system_sched import SystemScheduler
    from .core_sched import CoreScheduler

    register_scheduler("service", ServiceScheduler)
    register_scheduler("batch", BatchScheduler)
    register_scheduler("system", SystemScheduler)
    register_scheduler("_core", CoreScheduler)


_register_builtins()
