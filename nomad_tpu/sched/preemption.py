"""Preemption candidate selection (reference scheduler/preemption.go).

The greedy pick with cross-alloc dependencies is inherently sequential
(preemption.go:218-251) and stays on the host, but its inner scan —
`basicResourceDistance` + the max_parallel penalty over every remaining
candidate, re-evaluated per pick — is pure arithmetic over a (k x 3)
candidate resource matrix and runs vectorized
(`preemption_distances`).  The TPU select path evaluates preemption
only for nodes whose vectorized fit mask failed AND whose preemptible
resource sum covers the shortfall (tpu_stack._preempt_select), so the
per-node greedy runs on a small surviving set instead of the whole
walk being delegated to a shadow oracle.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..structs import (
    AllocatedResources,
    Allocation,
    ComparableResources,
    Node,
    PREEMPTION_PRIORITY_DELTA,
)

# Penalty applied when an alloc's task group has hit its migrate-stanza
# max_parallel in the current preemption set (reference preemption.go:13).
MAX_PARALLEL_PENALTY = 50.0


def basic_resource_distance(
    ask: ComparableResources, used: ComparableResources
) -> float:
    """Euclidean distance in (cpu, mem, disk) ask-relative coordinates
    (reference preemption.go:608 basicResourceDistance)."""
    mem_coord = cpu_coord = disk_coord = 0.0
    if ask.memory_mb > 0:
        mem_coord = (ask.memory_mb - used.memory_mb) / float(ask.memory_mb)
    if ask.cpu > 0:
        cpu_coord = (ask.cpu - used.cpu) / float(ask.cpu)
    if ask.disk_mb > 0:
        disk_coord = (ask.disk_mb - used.disk_mb) / float(ask.disk_mb)
    return math.sqrt(mem_coord**2 + cpu_coord**2 + disk_coord**2)


def score_for_task_group(
    ask: ComparableResources,
    used: ComparableResources,
    max_parallel: int,
    num_preempted: int,
) -> float:
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float((num_preempted + 1) - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def preemption_distances(
    needed: ComparableResources,
    res_matrix: np.ndarray,  # f64[k, 3] candidate (cpu, mem, disk)
    max_parallel: np.ndarray,  # i64[k]
    num_preempted: np.ndarray,  # i64[k]
) -> np.ndarray:
    """Vectorized `score_for_task_group` over k candidates: the
    distance arithmetic of preemption.go:608 + the max_parallel penalty
    of preemption.go:773, one fused pass instead of a Python loop per
    candidate per pick."""
    coords = np.zeros_like(res_matrix)
    ask = np.asarray(
        [needed.cpu, needed.memory_mb, needed.disk_mb], dtype=np.float64
    )
    nz = ask > 0
    coords[:, nz] = (ask[nz] - res_matrix[:, nz]) / ask[nz]
    dist = np.sqrt(np.sum(coords * coords, axis=1))
    over = (max_parallel > 0) & (num_preempted >= max_parallel)
    penalty = np.where(
        over,
        (num_preempted + 1 - max_parallel) * MAX_PARALLEL_PENALTY,
        0.0,
    )
    return dist + penalty


class Preemptor:
    """(reference preemption.go:96)"""

    def __init__(self, job_priority: int, job_ns_id: Tuple[str, str]) -> None:
        self.job_priority = job_priority
        self.job_ns_id = job_ns_id
        self.current_preemptions: Dict[Tuple[str, str, str], int] = {}
        self.alloc_resources: Dict[str, ComparableResources] = {}
        self.alloc_max_parallel: Dict[str, int] = {}
        self.current_allocs: List[Allocation] = []
        self.node_remaining: Optional[ComparableResources] = None

    def set_node(self, node: Node) -> None:
        remaining = node.comparable_resources()
        remaining.subtract(node.comparable_reserved_resources())
        self.node_remaining = remaining

    def set_candidates(self, allocs: List[Allocation]) -> None:
        self.current_allocs = []
        for alloc in allocs:
            if (alloc.namespace, alloc.job_id) == (
                self.job_ns_id[0],
                self.job_ns_id[1],
            ):
                continue
            max_parallel = 0
            if alloc.job is not None:
                tg = alloc.job.lookup_task_group(alloc.task_group)
                if tg is not None and tg.migrate is not None:
                    max_parallel = tg.migrate.max_parallel
            self.alloc_max_parallel[alloc.id] = max_parallel
            self.alloc_resources[alloc.id] = alloc.comparable_resources()
            self.current_allocs.append(alloc)

    def set_preemptions(self, allocs: List[Allocation]) -> None:
        self.current_preemptions = {}
        for alloc in allocs:
            key = (alloc.namespace, alloc.job_id, alloc.task_group)
            self.current_preemptions[key] = (
                self.current_preemptions.get(key, 0) + 1
            )

    def _num_preemptions(self, alloc: Allocation) -> int:
        return self.current_preemptions.get(
            (alloc.namespace, alloc.job_id, alloc.task_group), 0
        )

    def preempt_for_task_group(
        self, ask: AllocatedResources
    ) -> List[Allocation]:
        """Greedy distance-based preemption for CPU/mem/disk
        (reference preemption.go:198 PreemptForTaskGroup)."""
        needed = ask.comparable()
        asked = ask.comparable()

        node_remaining = ComparableResources(
            self.node_remaining.cpu,
            self.node_remaining.memory_mb,
            self.node_remaining.disk_mb,
            self.node_remaining.network_mbits,
        )
        for alloc in self.current_allocs:
            node_remaining.subtract(self.alloc_resources[alloc.id])

        groups = self._filter_and_group(self.current_allocs)

        best: List[Allocation] = []
        met = False
        available = ComparableResources(
            node_remaining.cpu,
            node_remaining.memory_mb,
            node_remaining.disk_mb,
            node_remaining.network_mbits,
        )

        for _priority, allocs in groups:
            allocs = list(allocs)
            # candidate resource matrix + penalty inputs, built once per
            # priority group; the greedy loop scores every remaining
            # candidate in one vectorized pass per pick
            res = np.asarray(
                [
                    [
                        self.alloc_resources[a.id].cpu,
                        self.alloc_resources[a.id].memory_mb,
                        self.alloc_resources[a.id].disk_mb,
                    ]
                    for a in allocs
                ],
                dtype=np.float64,
            ).reshape(len(allocs), 3)
            maxp = np.asarray(
                [self.alloc_max_parallel[a.id] for a in allocs],
                dtype=np.int64,
            )
            # current_preemptions is fixed for the duration of the
            # greedy loop (set_preemptions is the only mutator)
            nump = np.asarray(
                [self._num_preemptions(a) for a in allocs],
                dtype=np.int64,
            )
            alive = np.ones(len(allocs), dtype=bool)
            while alive.any() and not met:
                distances = preemption_distances(
                    needed, res, maxp, nump
                )
                distances[~alive] = math.inf
                best_index = int(np.argmin(distances))
                alive[best_index] = False
                closest = allocs[best_index]
                closest_resources = self.alloc_resources[closest.id]
                available.add(closest_resources)
                met, _dim = available.superset(asked)
                best.append(closest)
                needed.subtract(closest_resources)
            if met:
                break

        if not met:
            return []
        return self._filter_superset(best, node_remaining, asked)

    def preempt_for_network(self, ask, net_idx) -> Optional[List[Allocation]]:
        """Network preemption: not yet vectorized; conservative None keeps
        the node exhausted rather than mis-preempting
        (reference preemption.go:270 PreemptForNetwork)."""
        return None

    def preempt_for_device(self, ask, allocator) -> Optional[List[Allocation]]:
        """Device preemption (reference preemption.go:472): pick lowest
        net-priority preemptible allocs holding matching instances."""
        needed = ask.count
        candidates: List[Tuple[Allocation, int]] = []
        for alloc in self.current_allocs:
            if alloc.job is None:
                continue
            if self.job_priority - alloc.job.priority < PREEMPTION_PRIORITY_DELTA:
                continue
            held = 0
            ar = alloc.allocated_resources
            if ar is None:
                continue
            for tr in ar.tasks.values():
                for dev in tr.devices:
                    probe = "/".join(
                        x for x in (dev.vendor, dev.type, dev.name) if x
                    )
                    from ..structs import DeviceIdTuple

                    if DeviceIdTuple(dev.vendor, dev.type, dev.name).matches(
                        ask.name
                    ):
                        held += len(dev.device_ids)
            if held > 0:
                candidates.append((alloc, held))
        if not candidates:
            return None
        candidates.sort(key=lambda c: (-c[1], c[0].job.priority))
        chosen: List[Allocation] = []
        freed = 0
        for alloc, held in candidates:
            if freed >= needed:
                break
            chosen.append(alloc)
            freed += held
        if freed < needed:
            return None
        return chosen

    def _filter_and_group(
        self, current: List[Allocation]
    ) -> List[Tuple[int, List[Allocation]]]:
        """(reference preemption.go:666 filterAndGroupPreemptibleAllocs)"""
        by_priority: Dict[int, List[Allocation]] = {}
        for alloc in current:
            if alloc.job is None:
                continue
            if (
                self.job_priority - alloc.job.priority
                < PREEMPTION_PRIORITY_DELTA
            ):
                continue
            by_priority.setdefault(alloc.job.priority, []).append(alloc)
        return sorted(by_priority.items(), key=lambda kv: kv[0])

    def _filter_superset(
        self,
        best: List[Allocation],
        node_remaining: ComparableResources,
        asked: ComparableResources,
    ) -> List[Allocation]:
        """(reference preemption.go:702 filterSuperset)"""
        best = sorted(
            best,
            key=lambda a: basic_resource_distance(
                asked, self.alloc_resources[a.id]
            ),
            reverse=True,
        )
        available = ComparableResources(
            node_remaining.cpu,
            node_remaining.memory_mb,
            node_remaining.disk_mb,
            node_remaining.network_mbits,
        )
        filtered: List[Allocation] = []
        for alloc in best:
            filtered.append(alloc)
            available.add(self.alloc_resources[alloc.id])
            met, _dim = available.superset(asked)
            if met:
                break
        return filtered
