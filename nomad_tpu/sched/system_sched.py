"""System scheduler: one alloc per eligible node
(reference scheduler/system_sched.go).
"""
from __future__ import annotations

from dataclasses import replace as _replace
from typing import Dict, List, Optional

from ..structs import (
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_RUN,
    AllocatedResources,
    AllocatedSharedResources,
    Allocation,
    AllocMetric,
    Evaluation,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    filter_terminal_allocs,
    Node,
    Plan,
    PlanResult,
)
from .context import EvalContext
from .reconcile import (
    ALLOC_LOST,
    ALLOC_NODE_TAINTED,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    BLOCKED_EVAL_FAILED_PLACEMENTS,
)
from .scheduler import SetStatusError
from .stack import SystemStack
from .util import (
    adjust_queued_allocations,
    diff_system_allocs,
    evict_and_place,
    inplace_update,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5

SUPPORTED_TRIGGERS = {
    "job-register",
    "node-update",
    "failed-follow-up",
    "job-deregister",
    "rolling-update",
    "preemption",
    "deployment-watcher",
    "node-drain",
    "alloc-stop",
    "queued-allocs",
    "job-scaling",
}


class SystemScheduler:
    def __init__(
        self, state, planner, use_tpu: Optional[bool] = None,
        seed: Optional[int] = None,
    ) -> None:
        self.state = state
        self.planner = planner
        self.seed = seed
        if use_tpu is None:
            use_tpu = state.scheduler_config().tpu_scheduler_enabled
        self.use_tpu = use_tpu

        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack = None
        self.nodes: List[Node] = []
        self.nodes_by_dc: Dict[str, int] = {}
        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}

    def process(self, evaluation: Evaluation) -> None:
        self.eval = evaluation
        if evaluation.triggered_by not in SUPPORTED_TRIGGERS:
            desc = (
                f"scheduler cannot handle '{evaluation.triggered_by}' "
                "evaluation reason"
            )
            set_status(
                self.planner, evaluation, self.next_eval, None,
                self.failed_tg_allocs, EVAL_STATUS_FAILED, desc,
                self.queued_allocs, "",
            )
            return
        try:
            retry_max(
                MAX_SYSTEM_SCHEDULE_ATTEMPTS,
                self._process_once,
                lambda: progress_made(self.plan_result),
            )
        except SetStatusError as err:
            set_status(
                self.planner, self.eval, self.next_eval, None,
                self.failed_tg_allocs, err.eval_status, str(err),
                self.queued_allocs, "",
            )
            return
        set_status(
            self.planner, self.eval, self.next_eval, None,
            self.failed_tg_allocs, EVAL_STATUS_COMPLETE, "",
            self.queued_allocs, "",
        )

    def _process_once(self) -> bool:
        self.job = self.state.job_by_id(
            self.eval.namespace, self.eval.job_id
        )
        self.queued_allocs = {}

        if self.job is not None and not self.job.stopped():
            self.nodes, self.nodes_by_dc = ready_nodes_in_dcs(
                self.state, self.job.datacenters
            )
        else:
            self.nodes, self.nodes_by_dc = [], {}

        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = {}
        self.ctx = EvalContext(self.state, self.plan, seed=self.seed)
        self.stack = self._make_stack()
        if self.job is not None and not self.job.stopped():
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True

        if self.limit_reached and self.next_eval is None:
            stagger = (
                self.job.update.stagger_s
                if self.job is not None and self.job.update is not None
                else 30.0
            )
            self.next_eval = self.eval.next_rolling_eval(stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result
        adjust_queued_allocations(result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False
        full_commit, _e, _a = result.full_commit(self.plan)
        if not full_commit:
            return False
        return True

    def _make_stack(self):
        if self.use_tpu:
            from .tpu_stack import TPUSystemStack

            return TPUSystemStack(self.ctx, seed=self.seed)
        return SystemStack(self.ctx)

    def _compute_job_allocs(self) -> None:
        allocs = self.state.allocs_by_job(
            self.eval.namespace, self.eval.job_id
        )
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        live, terminal = filter_terminal_allocs(allocs)

        if self.job is None:
            from ..structs import Job

            job_for_diff = Job(id=self.eval.job_id, stop=True)
        else:
            job_for_diff = self.job
        diff = diff_system_allocs(
            job_for_diff, self.nodes, tainted, live, terminal
        )

        for e in diff.stop:
            self.plan.append_stopped_alloc(e.alloc, ALLOC_NOT_NEEDED)
        for e in diff.migrate:
            self.plan.append_stopped_alloc(e.alloc, ALLOC_NODE_TAINTED)
        for e in diff.lost:
            self.plan.append_stopped_alloc(
                e.alloc, ALLOC_LOST, ALLOC_CLIENT_STATUS_LOST
            )

        destructive, _inplace = inplace_update(
            self.ctx, self.eval, self.job, self.stack, diff.update
        )
        diff.update = destructive

        limit = len(diff.update)
        if (
            self.job is not None
            and not self.job.stopped()
            and self.job.update is not None
            and self.job.update.max_parallel > 0
        ):
            limit = self.job.update.max_parallel
        limit_box = [limit]
        self.limit_reached = evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit_box
        )

        if not diff.place:
            if self.job is not None and not self.job.stopped():
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for tup in diff.place:
            self.queued_allocs[tup.task_group.name] = (
                self.queued_allocs.get(tup.task_group.name, 0) + 1
            )
        self._compute_placements(diff.place)

    def _compute_placements(self, place) -> None:
        import time as _time

        node_by_id = {node.id: node for node in self.nodes}
        for missing in place:
            node = node_by_id.get(missing.alloc.node_id)
            if node is None:
                continue
            self.stack.set_nodes([node])
            t_select = _time.monotonic()
            option = self.stack.select(missing.task_group, None)
            # per-TG allocation latency (AllocMetric.AllocationTime)
            self.ctx.metrics.allocation_time_s = (
                _time.monotonic() - t_select
            )

            if option is None:
                if self.ctx.metrics.nodes_filtered > 0:
                    self.queued_allocs[missing.task_group.name] -= 1
                    continue
                metric = self.failed_tg_allocs.get(missing.task_group.name)
                if metric is not None:
                    metric.coalesced_failures += 1
                    continue
                self.ctx.metrics.nodes_available = self.nodes_by_dc
                self.failed_tg_allocs[missing.task_group.name] = (
                    self.ctx.metrics
                )
                self._add_blocked(node)
                continue

            self.ctx.metrics.nodes_available = self.nodes_by_dc
            resources = AllocatedResources(
                tasks=option.task_resources,
                shared=AllocatedSharedResources(
                    disk_mb=missing.task_group.ephemeral_disk.size_mb
                ),
            )
            if option.alloc_resources is not None:
                resources.shared.networks = option.alloc_resources.networks
                resources.shared.ports = option.alloc_resources.ports

            alloc = Allocation(
                namespace=self.job.namespace,
                eval_id=self.eval.id,
                name=missing.name,
                job_id=self.job.id,
                job=self.job,
                task_group=missing.task_group.name,
                metrics=self.ctx.metrics,
                node_id=option.node.id,
                node_name=option.node.name,
                allocated_resources=resources,
                desired_status=ALLOC_DESIRED_RUN,
                client_status=ALLOC_CLIENT_STATUS_PENDING,
            )
            if missing.alloc is not None and missing.alloc.id:
                alloc.previous_allocation = missing.alloc.id

            if option.preempted_allocs is not None:
                for stop in option.preempted_allocs:
                    self.plan.append_preempted_alloc(stop, alloc.id)

            self.plan.append_alloc(alloc)

    def _add_blocked(self, node: Node) -> None:
        e = self.ctx.eligibility
        escaped = e.has_escaped()
        class_eligibility = {} if escaped else e.get_classes()
        blocked = self.eval.create_blocked_eval(
            class_eligibility, escaped, e.quota_reached
        )
        blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        blocked.node_id = node.id
        self.planner.create_eval(blocked)
