"""Schedulers.

`oracle_*` modules are a faithful host-side re-expression of the
reference's pull-based iterator chain (scheduler/stack.go:116 Select) —
they serve as (a) the differential-parity oracle for the TPU kernel and
(b) the "stock" baseline the bench compares against.  `tpu_stack` is the
vectorized JAX backend.  `generic_sched`/`system_sched` sit above either
stack, mirroring scheduler/generic_sched.go and system_sched.go.
"""
from .scheduler import (  # noqa: F401
    BUILTIN_SCHEDULERS,
    new_scheduler,
    register_scheduler,
    SchedulerError,
    SetStatusError,
)
