"""Policy-weighted scoring: host-side weight-tensor assembly.

The score kernel (ops/score.py) accepts an optional ``PolicyTerms``
pytree — a per-(TG, node) throughput weight vector, a migration
stickiness vector, and per-policy scalar coefficients — fused into the
one broadcasted score pass.  This module is the host half: it resolves
a job's ``PolicySpec`` against the ``NOMAD_TPU_POLICY*`` knobs,
normalizes the Gavel-style throughput-by-node-class table ONCE (so the
serial rank iterator and the vectorized kernel consume float-identical
values), assembles arena-shaped numpy tensors from replicated state
(node classes via the existing interned ``node.class`` column, sticky
nodes via the job's live allocs), and caches the throughput tensor
keyed by (table epoch, job version, topo generation) so warm assembly
is O(1) like every other column.

Everything here reads only replicated state — the job spec, the node
table, and the alloc index — so fan-out followers assemble identical
tensors from their own store with zero new RPCs.

Two concrete policies ship end to end:

* **heterogeneity-aware throughput** — ``spec.throughput`` maps node
  class -> relative throughput (any positive scale); the assembler
  normalizes by the table max and the kernel appends
  ``coef * tput_norm[node]`` to the score mean for EVERY candidate
  (zeros included: an unknown class pulls the mean down).
* **migration / reschedule cost** — when this TG has live allocs
  (older than ``min_runtime_s``), every node NOT currently hosting one
  pays a ``-migration_coefficient`` penalty, appended only where
  non-zero (the node-reschedule-penalty convention: the incumbent's
  score mean is untouched, movers are dragged down), so drains and
  mass replans prefer in-place replacement over churn.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, NamedTuple, Optional, Set, Tuple

import numpy as np

from ..state.node_table import MISSING

# zero-registered at Server construction (the same absence-of-series
# contract as storm.* / mesh.*: no policy.* series must mean "no
# policy-weighted select ever ran", never "not exported")
POLICY_COUNTERS = (
    "policy.assemblies",
    "policy.cache_hits",
    "policy.cache_misses",
    "policy.evals",
    "policy.storm_evals",
)
POLICY_GAUGES = (
    "policy.cache_size",
)


def policy_enabled() -> bool:
    """NOMAD_TPU_POLICY=0 disables the policy layer entirely (jobs
    carrying a PolicySpec score as policy-less).  Default on — inert
    without a job-level spec."""
    return os.environ.get("NOMAD_TPU_POLICY", "1") != "0"


def _coef_override(knob: str) -> Optional[float]:
    raw = os.environ.get(knob, "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


class ResolvedPolicy(NamedTuple):
    """A job's PolicySpec after knob resolution and normalization.
    ``tput_norm`` is the throughput table divided by its max — computed
    exactly once here so the serial oracle's per-node dict lookup and
    the vectorized tensor gather see float-identical values (the
    division happens on one side only, never twice)."""

    tput_norm: Tuple[Tuple[str, float], ...]  # hashable normalized table
    has_tput: bool
    tput_coef: float
    mig_coef: float
    min_runtime_s: float

    def tput_value(self, node_class: str) -> float:
        for cls, v in self.tput_norm:
            if cls == node_class:
                return v
        return 0.0


def resolve(job) -> Optional[ResolvedPolicy]:
    """The job's effective policy, or None when the layer is off, the
    job carries no spec, or the spec is inert."""
    spec = getattr(job, "policy", None)
    if spec is None or not policy_enabled():
        return None
    tput_coef = _coef_override("NOMAD_TPU_POLICY_TPUT_COEF")
    if tput_coef is None:
        tput_coef = float(spec.throughput_coefficient)
    mig_coef = _coef_override("NOMAD_TPU_POLICY_MIG_COEF")
    if mig_coef is None:
        mig_coef = float(spec.migration_coefficient)
    table = dict(spec.throughput or {})
    norm: Tuple[Tuple[str, float], ...] = ()
    if table:
        maxv = max(table.values())
        if maxv > 0:
            norm = tuple(
                sorted((cls, float(v) / maxv) for cls, v in table.items())
            )
    has_tput = bool(norm)
    if not has_tput and mig_coef == 0.0:
        return None
    return ResolvedPolicy(
        tput_norm=norm,
        has_tput=has_tput,
        tput_coef=tput_coef,
        mig_coef=mig_coef,
        min_runtime_s=float(spec.min_runtime_s),
    )


# ---------------------------------------------------------------------------
# tensor assembly
# ---------------------------------------------------------------------------


def _cache_capacity() -> int:
    try:
        return max(1, int(os.environ.get("NOMAD_TPU_POLICY_CACHE", "64")))
    except ValueError:
        return 64


class _TputCache:
    """LRU of assembled throughput tensors keyed by everything that can
    change one: the table identity (epoch survives snapshot-restore
    table swaps), the job's policy version, the topology generation
    (node joins / class re-fingerprints), the arena capacity (grows
    reshape the tensor) and the dtype (f64 parity path vs f32 device
    mirror)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, np.ndarray]" = OrderedDict()

    def get(self, key: tuple) -> Optional[np.ndarray]:
        with self._lock:
            tensor = self._entries.get(key)
            if tensor is not None:
                self._entries.move_to_end(key)
            return tensor

    def put(self, key: tuple, tensor: np.ndarray) -> None:
        with self._lock:
            self._entries[key] = tensor
            self._entries.move_to_end(key)
            cap = _cache_capacity()
            while len(self._entries) > cap:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_TPUT_CACHE = _TputCache()


def tput_tensor(
    resolved: ResolvedPolicy,
    job,
    table,
    dtype=np.float64,
    metrics=None,
) -> np.ndarray:
    """Arena-shaped normalized-throughput vector for this job's policy:
    ``out[row] = tput_norm[node.class]`` (0 for vacant rows and unknown
    classes).  Cached keyed by (table epoch, job version, topo
    generation): warm assembly is a dict hit; a cold one is one
    interner-sized python loop plus one vectorized gather."""
    key = (
        table.epoch,
        job.namespace,
        job.id,
        job.version,
        resolved.tput_norm,
        resolved.has_tput,
        table.topo_generation,
        table.capacity,
        np.dtype(dtype).str,
    )
    cached = _TPUT_CACHE.get(key)
    if cached is not None:
        if metrics is not None:
            metrics.incr("policy.cache_hits")
        return cached
    col = table.column("node.class")
    # per-code lookup table, then one gather over the arena codes;
    # MISSING (vacant row / classless node) maps to 0.0
    lookup = dict(resolved.tput_norm)
    code_values = np.array(
        [lookup.get(v, 0.0) for v in col.interner.values] + [0.0],
        dtype=dtype,
    )
    tensor = code_values[np.where(col.codes == MISSING, -1, col.codes)]
    tensor = np.ascontiguousarray(tensor, dtype=dtype)
    _TPUT_CACHE.put(key, tensor)
    if metrics is not None:
        metrics.incr("policy.cache_misses")
        metrics.incr("policy.assemblies")
        metrics.set_gauge("policy.cache_size", float(len(_TPUT_CACHE)))
    return tensor


def clear_tput_cache() -> None:
    """Test hook."""
    _TPUT_CACHE.clear()


def sticky_node_ids(
    resolved: ResolvedPolicy,
    job,
    tg_name: str,
    state,
    now: Optional[float] = None,
) -> Set[str]:
    """Node ids currently hosting a live (non-terminal) alloc of this
    job+TG older than ``min_runtime_s`` — the migration-cost policy's
    stickiness set.  Both the serial PolicyIterator and the vectorized
    tensor derive from THIS set so membership is identical."""
    if resolved.mig_coef == 0.0:
        return set()
    cutoff = None
    if resolved.min_runtime_s > 0.0:
        cutoff = (time.time() if now is None else now) - resolved.min_runtime_s
    out: Set[str] = set()
    for alloc in state.allocs_by_job(job.namespace, job.id):
        if alloc.task_group != tg_name or alloc.terminal_status():
            continue
        if cutoff is not None and alloc.create_time > cutoff:
            continue
        if alloc.node_id:
            out.add(alloc.node_id)
    return out


def migration_vector(
    sticky: Set[str],
    table,
    dtype=np.float64,
) -> np.ndarray:
    """Arena-shaped migration-cost vector from a sticky-node-id set:
    ``-1`` on every row EXCEPT the sticky ones, all-zero when the set
    is empty (fresh placement — no incumbent, no cost).  The kernel
    multiplies by ``mig_coef`` and appends only where non-zero, so the
    incumbent's score mean is untouched while every other node pays
    the reschedule penalty — the ``node-reschedule-penalty`` shape.
    A positive bonus on the incumbent would backfire under Nomad's
    mean-of-components scoring: any bonus below the node's other
    component mean LOWERS it."""
    if not sticky:
        return np.zeros(table.capacity, dtype=dtype)
    mig = np.full(table.capacity, -1.0, dtype=dtype)
    for node_id in sticky:
        row = table.row_of.get(node_id)
        if row is not None:
            mig[row] = 0.0
    return mig
