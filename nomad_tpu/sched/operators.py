"""Constraint/affinity operator semantics.

Value-level implementation of the reference's `scheduler/feasible.go:750
checkConstraint` and helpers (checkLexicalOrder:799, checkVersionMatch:826,
checkRegexpMatch:893, checkSetContainsAll:925, checkSetContainsAny:958).
Shared by the host oracle chain and by the LUT compiler in
`nomad_tpu/ops/constraints.py`, which evaluates these exact semantics over
a column's vocabulary to produce device-side boolean lookup tables.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..structs import (
    CONSTRAINT_ATTRIBUTE_IS_NOT_SET,
    CONSTRAINT_ATTRIBUTE_IS_SET,
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER,
    CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL,
    CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
)


# ---------------------------------------------------------------------------
# Version parsing (semantics of hashicorp/go-version and blang/semver as the
# reference uses them)
# ---------------------------------------------------------------------------

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)(?:-([0-9A-Za-z\-~]+(?:\.[0-9A-Za-z\-~]+)*))?"
    r"(?:\+([0-9A-Za-z\-~]+(?:\.[0-9A-Za-z\-~]+)*))?$"
)


class Version:
    __slots__ = ("segments", "prerelease")

    def __init__(self, segments: Tuple[int, ...], prerelease: str) -> None:
        self.segments = segments
        self.prerelease = prerelease

    @classmethod
    def parse(cls, raw: str) -> Optional["Version"]:
        m = _VERSION_RE.match(raw.strip())
        if not m:
            return None
        segments = tuple(int(p) for p in m.group(1).split("."))
        # normalize to 3 segments like go-version
        while len(segments) < 3:
            segments = segments + (0,)
        return cls(segments, m.group(2) or "")

    def _pre_key(self):
        # a version with a prerelease sorts before the same version without
        if not self.prerelease:
            return (1,)
        parts: List = [0]
        for piece in self.prerelease.split("."):
            if piece.isdigit():
                parts.append((0, int(piece), ""))
            else:
                parts.append((1, 0, piece))
        return tuple(parts)

    def compare(self, other: "Version") -> int:
        a, b = self.segments, other.segments
        length = max(len(a), len(b))
        a = a + (0,) * (length - len(a))
        b = b + (0,) * (length - len(b))
        if a != b:
            return -1 if a < b else 1
        ka, kb = self._pre_key(), other._pre_key()
        if ka == kb:
            return 0
        return -1 if ka < kb else 1


_CONSTRAINT_OP_RE = re.compile(r"^\s*(>=|<=|!=|=|>|<|~>)?\s*(.*)$")


def check_version_constraint(
    version_str: str, constraint_str: str, strict_semver: bool = False
) -> bool:
    """Evaluate a comma-separated version constraint expression, e.g.
    ">= 1.2, < 2.0" (reference feasible.go:826 checkVersionMatch)."""
    vers = Version.parse(version_str)
    if vers is None:
        return False
    for part in constraint_str.split(","):
        m = _CONSTRAINT_OP_RE.match(part.strip())
        if not m:
            return False
        op = m.group(1) or "="
        target = Version.parse(m.group(2))
        if target is None:
            return False
        if strict_semver and op != "~>":
            # blang-style semver: prereleases only match explicitly equal asks
            pass
        cmp = vers.compare(target)
        if op == "=" and cmp != 0:
            return False
        if op == "!=" and cmp == 0:
            return False
        if op == ">" and cmp <= 0:
            return False
        if op == ">=" and cmp < 0:
            return False
        if op == "<" and cmp >= 0:
            return False
        if op == "<=" and cmp > 0:
            return False
        if op == "~>":
            # pessimistic operator: >= target and < next significant release
            if cmp < 0:
                return False
            segs = target.segments
            raw = m.group(2).strip().lstrip("v").split("-")[0]
            n_specified = len(raw.split("."))
            if n_specified >= 2:
                upper_segs = list(segs[: n_specified - 1])
                upper_segs[-1] += 1
                upper = Version(tuple(upper_segs + [0] * (3 - len(upper_segs))), "")
                if vers.compare(upper) >= 0:
                    return False
    return True


# ---------------------------------------------------------------------------
# Operator dispatch
# ---------------------------------------------------------------------------


def check_lexical_order(op: str, lval: str, rval: str) -> bool:
    if op == "<":
        return lval < rval
    if op == "<=":
        return lval <= rval
    if op == ">":
        return lval > rval
    if op == ">=":
        return lval >= rval
    return False


def check_set_contains_all(lval: str, rval: str) -> bool:
    have = {p.strip() for p in lval.split(",")}
    return all(p.strip() in have for p in rval.split(","))


def check_set_contains_any(lval: str, rval: str) -> bool:
    have = {p.strip() for p in lval.split(",")}
    return any(p.strip() in have for p in rval.split(","))


def check_regexp_match(
    lval: str, rval: str, cache: Optional[Dict[str, "re.Pattern"]] = None
) -> bool:
    pattern = cache.get(rval) if cache is not None else None
    if pattern is None:
        try:
            pattern = re.compile(rval)
        except re.error:
            return False
        if cache is not None:
            cache[rval] = pattern
    return pattern.search(lval) is not None


def check_constraint(
    operand: str,
    lval: Optional[str],
    rval: Optional[str],
    lfound: bool,
    rfound: bool,
    regex_cache: Optional[Dict] = None,
    version_cache: Optional[Dict] = None,
) -> bool:
    """Exact semantics of the reference's checkConstraint
    (feasible.go:750)."""
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        # handled by dedicated iterators, always pass here
        return True

    if operand in ("=", "==", "is"):
        return lfound and rfound and lval == rval
    if operand in ("!=", "not"):
        # NB: the reference compares values without requiring found-ness
        # here (a missing attr is != any value)
        return lval != rval or lfound != rfound
    if operand in ("<", "<=", ">", ">="):
        return (
            lfound
            and rfound
            and isinstance(lval, str)
            and isinstance(rval, str)
            and check_lexical_order(operand, lval, rval)
        )
    if operand == CONSTRAINT_ATTRIBUTE_IS_SET:
        return lfound
    if operand == CONSTRAINT_ATTRIBUTE_IS_NOT_SET:
        return not lfound
    if operand == CONSTRAINT_VERSION:
        return (
            lfound
            and rfound
            and _cached_version_check(lval, rval, False, version_cache)
        )
    if operand == CONSTRAINT_SEMVER:
        return (
            lfound
            and rfound
            and _cached_version_check(lval, rval, True, version_cache)
        )
    if operand == CONSTRAINT_REGEX:
        return lfound and rfound and check_regexp_match(lval, rval, regex_cache)
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return lfound and rfound and check_set_contains_all(lval, rval)
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return lfound and rfound and check_set_contains_any(lval, rval)
    return False


def _cached_version_check(
    lval: str, rval: str, strict: bool, cache: Optional[Dict]
) -> bool:
    if cache is None:
        return check_version_constraint(lval, rval, strict)
    key = (lval, rval, strict)
    hit = cache.get(key)
    if hit is None:
        hit = check_version_constraint(lval, rval, strict)
        cache[key] = hit
    return hit


def check_affinity(
    operand: str,
    lval,
    rval,
    lfound: bool,
    rfound: bool,
    regex_cache=None,
    version_cache=None,
) -> bool:
    """(reference feasible.go:789 checkAffinity)"""
    return check_constraint(
        operand, lval, rval, lfound, rfound, regex_cache, version_cache
    )
