"""Scheduler test harness (reference scheduler/testing.go:43 Harness).

A real StateStore plus an in-memory Planner that records plans/evals and
applies plans directly via `upsert_plan_results`.  This is the fixture the
whole differential-parity suite is built on (SURVEY.md section 4.2).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..state.store import StateSnapshot, StateStore
from ..structs import (
    Evaluation,
    Plan,
    PlanResult,
)


class Harness:
    def __init__(self, store: Optional[StateStore] = None) -> None:
        self.store = store or StateStore()
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []
        self.reject_plan = False
        # reject but still apply: exercises the refresh/retry path
        self.reject_and_apply = False

    # -- Planner interface ---------------------------------------------

    def submit_plan(
        self, plan: Plan
    ) -> Tuple[PlanResult, Optional[StateSnapshot]]:
        self.plans.append(plan)
        if self.reject_plan and not self.reject_and_apply:
            return PlanResult(), self.store.snapshot()

        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=self.store.latest_index() + 1,
        )
        index = self.store.upsert_plan_results(result, plan.eval_id)
        result.alloc_index = index
        if self.reject_and_apply:
            return result, self.store.snapshot()
        return result, None

    def update_eval(self, evaluation: Evaluation) -> None:
        self.evals.append(evaluation)

    def create_eval(self, evaluation: Evaluation) -> None:
        self.create_evals.append(evaluation)

    def reblock_eval(self, evaluation: Evaluation) -> None:
        self.reblock_evals.append(evaluation)

    # -- helpers --------------------------------------------------------

    def snapshot(self) -> StateSnapshot:
        return self.store.snapshot()

    def process(self, factory, evaluation: Evaluation, **kwargs) -> None:
        scheduler = factory(self.snapshot(), self, **kwargs)
        scheduler.process(evaluation)
        return scheduler
