"""Eval flight recorder: end-to-end per-eval span tracing.

The batch pipeline's aggregate telemetry (`batch_worker.*` summaries,
`replay.*` counters) says *that* a stage is slow, never *which eval*
paid for it.  This module records one bounded trace per evaluation —
spans (named, timed intervals) and events (zero-duration marks) —
across every thread the eval's lifecycle touches: broker dequeue,
batch-worker gulp/simulate/assemble/launch/fetch, speculative replay
on the pool, the commit wave's ordering wait and conflict verdicts,
plan verification/apply, and the store's commit index.

Design constraints (always-on tracing must be free enough to forget):

* **O(1) per span.**  A span append is a list append under a per-trace
  lock; no allocation beyond the span record itself.
* **Bounded retention.**  One process-wide ring of `TRACE_RING` traces
  (active and completed alike — a trace that outlives the ring under
  load is dropped, never grown), `MAX_SPANS` spans per trace
  (overflow counts into `dropped`).
* **Monotonic timestamps.**  `time.monotonic()` everywhere; one
  wall-clock anchor per trace for display.
* **Opt-out, not opt-in.**  `NOMAD_TPU_TRACE=0` turns every call into
  a no-op (`Tracer.set_enabled` flips it at runtime for benches).

The tracer is a process-wide singleton (`TRACE`), like the logging
module: the broker, store and plan applier have no server reference,
and eval ids are globally unique, so per-server registries would only
add plumbing.  Cross-thread attribution is by eval id — every call
site knows which eval it is working for — with per-(trace, thread)
open-span stacks providing parent/child nesting.

Span names used in instrumented modules must be declared in
``SPAN_NAMES`` below; ``tools/check_stage_accounting.py`` lints
``batch_worker.py`` and ``plan_apply.py`` against this registry so a
renamed stage can't silently orphan its dashboard queries.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# retained traces (completed or in flight); at ~30 spans x ~150 bytes
# per trace this bounds the recorder near 5 MB
TRACE_RING = 1024
# spans per trace before overflow counting kicks in
MAX_SPANS = 256

# the documented span/event name registry.  Every `.span/.add_span/
# .event` literal in batch_worker.py and plan_apply.py must appear
# here (tools/check_stage_accounting.py); names from other modules are
# registered too so the registry is the one place to look up a trace.
SPAN_NAMES = frozenset(
    {
        # broker lifecycle
        "broker.dequeue",
        # batch pipeline stages (per-eval attribution of the
        # batch_worker.timings stages; chunk-wide spans carry a
        # `members` attr so aggregate sums match the stage timings)
        "batch_worker.gulp",
        # continuous micro-batching: `admit` spans an admission round's
        # gate+simulate+assemble work on every admitted eval (with a
        # `members` attr like the other chunk-wide stages);
        # `admit_deferred` marks an eval that arrived mid-chain but
        # failed an admission gate and was parked for the next gulp
        "batch_worker.admit",
        "batch_worker.admit_deferred",
        "batch_worker.simulate",
        "batch_worker.assemble",
        "batch_worker.launch",
        "batch_worker.fetch",
        # sharded (NOMAD_TPU_MESH) chunk dispatch/realize: the same
        # pipeline positions as launch/fetch, under their own names so
        # mesh time is separable on every trace-keyed dashboard (and
        # budgeted separately by the supervisor's stage watchdogs)
        "batch_worker.mesh_launch",
        "batch_worker.mesh_fetch",
        # global storm solver (NOMAD_TPU_STORM=1): `storm_gulp` marks
        # a family backlog drained for one coalesced solve (with the
        # member's FIFO position), `storm_solve` spans the single
        # device-side assignment solve on every member (members attr
        # like the other chunk-wide stages), `storm_decompose` the
        # per-eval plan decomposition, and `storm.fallback` marks a
        # member handed back to the serial chain (gate reason /
        # unsolved row / commit rescore / whole-storm crash) — never
        # a dropped eval
        "batch_worker.storm_gulp",
        # policy-weighted scoring (sched/policy.py): spans one storm
        # member's weight-tensor assembly — cached-throughput lookup
        # plus the live-alloc stickiness scan — inside staging
        "batch_worker.policy_assemble",
        "batch_worker.storm_solve",
        "batch_worker.storm_decompose",
        "storm.fallback",
        "batch_worker.replay",
        "batch_worker.sequential",
        "batch_worker.fallback",
        # optimistic parallel replay
        "replay.speculate",
        "replay.serial_required",
        "replay.commit_wait",
        "replay.commit",
        "replay.conflict",
        "replay.serial_fallback",
        # sequential worker
        "worker.invoke_scheduler",
        # accelerator supervisor (nomad_tpu/device): failover
        # incidents get their own trace (``device:failover:<n>``,
        # rooted at device.incident); device.watchdog_trip also lands
        # on the eval whose guarded stage tripped
        "device.incident",
        "device.failover",
        "device.watchdog_trip",
        "device.state_change",
        "device.flush",
        "device.probe",
        "device.rewarm",
        "device.recover",
        # overload control plane: `ingress.shed` roots one incident
        # trace (``overload:<n>``) per excursion from NORMAL — its
        # annotations carry the trigger signals and final shed counts;
        # `server.node_down_wave` roots one trace per batched mass
        # node-death transition (``node_down_wave:<n>``) naming the
        # wave's node count, replan evals and storm family
        "ingress.shed",
        # `overload.mode_change` lands on BOTH the overload incident
        # trace and every in-flight eval trace at the moment the mode
        # ladder moves, so a shed or degraded eval's waterfall says
        # which regime it ran under without joining against /v1/overload
        "overload.mode_change",
        "server.node_down_wave",
        # follower scheduling fan-out (NOMAD_TPU_FANOUT=1):
        # `fanout.remote_dequeue` spans the lease RPC on every eval a
        # follower dequeued from the leader's broker (members = lease
        # batch size), `fanout.plan_submit` spans the remote
        # serialized-commit round trip into the leader's plan queue
        "fanout.remote_dequeue",
        "fanout.plan_submit",
        # cluster-scope observability: `fanout.remote_span_ship`
        # marks a follower exporting its recorded span segment back
        # to the leader (piggybacked on the settle/submit RPC;
        # spans = segment size), `cluster.fanin` spans a leader's
        # fan-in query over the cluster transport (servers = peers
        # asked, unreachable = peers that timed out)
        "fanout.remote_span_ship",
        "cluster.fanin",
        # multi-region federation: `federation.forward` roots one
        # trace (``federation:<n>``) per cross-region call — its
        # spans carry the target region, op, attempt number and the
        # server that finally answered; `federation.fanout` roots one
        # trace (``federation:fanout:<id>``) per Multiregion job
        # fanned from the home region's leader, with a forward span
        # per target region
        "federation.forward",
        "federation.fanout",
        # plan pipeline + state commit
        "plan.evaluate",
        "plan.apply",
        # leadership failover: the applier rejected an in-flight plan
        # because leadership was revoked (the submitting worker nacks
        # the eval for redelivery under the next leadership)
        "plan.not_leader",
        "store.commit",
        "fsm.apply",
    }
)


class _NullSpan:
    """Reusable no-op context manager for disabled/unknown traces."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _SpanCtx:
    __slots__ = ("_trace", "_name", "_attrs", "_sid")

    def __init__(self, trace: "Trace", name: str, attrs: dict) -> None:
        self._trace = trace
        self._name = name
        self._attrs = attrs
        self._sid = -1

    def __enter__(self):
        self._sid = self._trace.open_span(
            self._name, time.monotonic(), self._attrs
        )
        return self

    def __exit__(self, *exc):
        self._trace.close_span(self._sid, time.monotonic())
        return False


class Trace:
    """One eval's recorded lifecycle.  Span records are small lists
    ``[sid, parent, name, start, duration, thread, attrs]`` —
    ``duration`` stays None while the span is open."""

    __slots__ = (
        "eval_id",
        "trace_id",
        "t0",
        "wall0",
        "t_end",
        "spans",
        "attrs",
        "outcome",
        "finished",
        "dropped",
        "orphans",
        "_open",
        "_seq",
        "_lock",
        "_shipped",
    )

    def __init__(self, eval_id: str, gen: int, attrs: dict) -> None:
        self.eval_id = eval_id
        self.trace_id = f"{eval_id}#{gen}"
        self.t0 = time.monotonic()
        self.wall0 = time.time()
        self.t_end: Optional[float] = None
        self.spans: List[list] = []
        self.attrs = dict(attrs)
        self.outcome: Optional[str] = None
        self.finished = False
        self.dropped = 0
        self.orphans = 0
        # thread id -> stack of open span ids (nesting is per thread;
        # cross-thread spans attach at that thread's current depth)
        self._open: Dict[int, List[int]] = {}
        self._seq = 0
        self._lock = threading.Lock()
        # span ids already exported by export_segment (segment traces
        # on fan-out followers only; empty everywhere else)
        self._shipped: set = set()

    # -- recording -----------------------------------------------------

    def _parent_locked(self, tid: int) -> Optional[int]:
        stack = self._open.get(tid)
        return stack[-1] if stack else None

    def open_span(self, name: str, start: float, attrs: dict) -> int:
        tid = threading.get_ident()
        with self._lock:
            if len(self.spans) >= MAX_SPANS or start < self.t0:
                # over the cap, or a write from a SUPERSEDED attempt:
                # after a redelivery the old attempt may still be
                # running, and its by-eval-id writes resolve to this
                # (newer) trace — an interval that began before this
                # trace did belongs to the old generation, not here
                self.dropped += 1
                return -1
            sid = self._seq
            self._seq += 1
            self.spans.append(
                [
                    sid,
                    self._parent_locked(tid),
                    name,
                    start,
                    None,
                    threading.current_thread().name,
                    attrs,
                ]
            )
            self._open.setdefault(tid, []).append(sid)
            return sid

    def close_span(self, sid: int, end: float) -> None:
        if sid < 0:
            return
        tid = threading.get_ident()
        with self._lock:
            stack = self._open.get(tid)
            if stack and sid in stack:
                # pop through sid: a crash that skipped inner exits
                # must not leave phantom parents for later spans
                while stack and stack.pop() != sid:
                    pass
                if not stack:
                    self._open.pop(tid, None)
            for span in self.spans:
                if span[0] == sid:
                    span[4] = end - span[3]
                    return

    def add_span(
        self, name: str, start: float, duration: float, attrs: dict
    ) -> None:
        """Record an already-timed interval (stage times measured once
        per chunk/run and attributed to each member eval)."""
        tid = threading.get_ident()
        with self._lock:
            if len(self.spans) >= MAX_SPANS or start < self.t0:
                # see open_span: pre-t0 starts are a superseded
                # attempt's writes (best-effort — a stale write whose
                # clock reads after this trace began is
                # indistinguishable and slips through)
                self.dropped += 1
                return
            sid = self._seq
            self._seq += 1
            self.spans.append(
                [
                    sid,
                    self._parent_locked(tid),
                    name,
                    start,
                    duration,
                    threading.current_thread().name,
                    attrs,
                ]
            )

    def annotate(self, attrs: dict) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def finish(self, outcome: str) -> None:
        with self._lock:
            if self.finished:
                return
            self.finished = True
            self.t_end = time.monotonic()
            # a batch-worker path may have annotated a richer outcome
            # ("speculative", "prescored", "sequential") — but only a
            # successful ack consumes it: a nack or a redelivery
            # supersede describes an attempt that did NOT stick, and
            # must not masquerade as the annotated success
            annotated = self.attrs.pop("outcome", None)
            self.outcome = (
                annotated if annotated and outcome == "ack" else outcome
            )
            self.orphans = sum(
                1 for s in self.spans if s[4] is None
            )

    # -- cross-server segment shipping ---------------------------------

    def export_segment(self, server_id: str) -> Optional[Dict]:
        """Export the CLOSED spans not shipped by a previous export as
        a wire segment (fan-out followers piggyback this on the settle
        / submit RPC).  Offsets are seconds relative to this trace's
        ``t0``; the segment carries the trace's ``wall0`` wall-clock
        anchor so the receiver can map them onto its own monotonic
        clock (clock skew between hosts shows up as shifted lanes —
        trace_report flags skew-suspect gaps rather than us trusting
        cross-host monotonic deltas)."""
        with self._lock:
            fresh = [
                s
                for s in self.spans
                if s[4] is not None and s[0] not in self._shipped
            ]
            for s in fresh:
                self._shipped.add(s[0])
            spans = [
                {
                    "id": s[0],
                    "parent": s[1],
                    "name": s[2],
                    "off": s[3] - self.t0,
                    "dur": s[4],
                    "thread": s[5],
                    "attrs": dict(s[6]),
                }
                for s in fresh
            ]
            attrs = dict(self.attrs)
        if not spans and "outcome" not in attrs:
            return None
        return {
            "trace_id": self.trace_id,
            "server_id": server_id,
            "wall0": self.wall0,
            "spans": spans,
            "attrs": attrs,
        }

    def absorb_segment(self, segment: Dict) -> int:
        """Merge a shipped segment's spans into this trace: remote
        offsets are re-anchored via the wall-clock deltas, span ids are
        remapped into this trace's sequence (parent links within the
        segment batch are preserved; a parent shipped in an *earlier*
        batch attaches flat), and every span is stamped with the
        shipping ``server_id``.  Bypasses the pre-``t0`` staleness
        guard on purpose — segment routing already matched the full
        trace id, so generation confusion is impossible here and a
        skewed remote clock must not silently drop spans."""
        base = self.t0 + (segment.get("wall0", self.wall0) - self.wall0)
        server_id = segment.get("server_id", "")
        absorbed = 0
        with self._lock:
            remap: Dict[int, int] = {}
            for s in segment.get("spans", ()):
                if len(self.spans) >= MAX_SPANS:
                    self.dropped += 1
                    continue
                sid = self._seq
                self._seq += 1
                remap[s["id"]] = sid
                attrs = dict(s.get("attrs") or {})
                if server_id:
                    attrs.setdefault("server_id", server_id)
                self.spans.append(
                    [
                        sid,
                        remap.get(s.get("parent")),
                        s["name"],
                        base + s["off"],
                        s["dur"],
                        s.get("thread", ""),
                        attrs,
                    ]
                )
                absorbed += 1
            if self.finished:
                # late segment into an already-settled trace (the
                # normal nack/redelivery race): keep the orphan count
                # honest for the spans that just landed
                self.orphans = sum(
                    1 for s in self.spans if s[4] is None
                )
        return absorbed

    # -- serialization -------------------------------------------------

    def duration_ms(self) -> Optional[float]:
        if self.t_end is None:
            return None
        end = self.t_end
        with self._lock:
            for s in self.spans:
                if s[4] is not None:
                    end = max(end, s[3] + s[4])
        return (end - self.t0) * 1000.0

    def summary(self) -> Dict:
        return {
            "eval_id": self.eval_id,
            "trace_id": self.trace_id,
            "start": self.wall0,
            "outcome": self.outcome,
            "complete": self.finished,
            "duration_ms": self.duration_ms(),
            "spans": len(self.spans),
            "dropped": self.dropped,
            "orphans": self.orphans,
            "attrs": dict(self.attrs),
        }

    def to_dict(self) -> Dict:
        out = self.summary()
        with self._lock:
            out["spans"] = [
                {
                    "id": sid,
                    "parent": parent,
                    "name": name,
                    "off_ms": (start - self.t0) * 1000.0,
                    "dur_ms": (
                        duration * 1000.0
                        if duration is not None
                        else None
                    ),
                    "thread": thread,
                    "attrs": dict(attrs),
                }
                for sid, parent, name, start, duration, thread, attrs
                in self.spans
            ]
        return out


class Tracer:
    def __init__(self, ring: int = TRACE_RING) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._ring_cap = ring
        # newest trace per eval id (ring members only) — the append
        # surface every instrumented call site goes through
        self._by_id: Dict[str, Trace] = {}
        # follower-side recording buffers for evals leased from a
        # remote leader, keyed by eval id: they carry the LEADER's
        # trace id, collect this server's pipeline spans, and are
        # shipped back (export_segment) rather than retained — they
        # never enter the ring
        self._segments: Dict[str, Trace] = {}
        self._gen = itertools.count()
        self.enabled = os.environ.get("NOMAD_TPU_TRACE", "1") != "0"
        # happens-before sanitizer (NOMAD_TPU_TSAN=1)
        from .tsan import maybe_instrument

        maybe_instrument(self, "Tracer")

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    # -- lifecycle -----------------------------------------------------

    def begin(
        self, eval_id: str, root_span: str = "broker.dequeue", **attrs
    ) -> None:
        """Start (or restart, on redelivery) an eval's trace; records
        ``root_span`` (default `broker.dequeue`) as the root event —
        non-eval traces (the device supervisor's failover incidents)
        pass their own root name."""
        if not self.enabled or not eval_id:
            return
        trace = Trace(eval_id, next(self._gen), attrs)
        with self._lock:
            prior = self._by_id.get(eval_id)
            if prior is not None and not prior.finished:
                prior.finish("superseded")
            self._by_id[eval_id] = trace
            self._ring.append(trace)
            while len(self._ring) > self._ring_cap:
                evicted = self._ring.popleft()
                if self._by_id.get(evicted.eval_id) is evicted:
                    del self._by_id[evicted.eval_id]
        trace.add_span(root_span, trace.t0, 0.0, attrs)

    def finish(self, eval_id: str, outcome: str) -> None:
        if not self.enabled:
            return
        trace = self._by_id.get(eval_id)
        if trace is not None:
            trace.finish(outcome)

    # -- cross-server propagation --------------------------------------

    def export_context(self, eval_id: str) -> Optional[Dict]:
        """Trace context shipped with a remote broker lease: the full
        trace id (generation counters are per-process, so the string is
        the only cross-server identity) plus the wall-clock anchor the
        follower needs to re-anchor its segment offsets."""
        trace = self._by_id.get(eval_id)
        if trace is None:
            return None
        return {"trace_id": trace.trace_id, "wall0": trace.wall0}

    def begin_segment(self, eval_id: str, ctx: Dict, **attrs) -> None:
        """Follower side of lease propagation: open a local recording
        segment under the LEADER's trace id.  Instrumented call sites
        resolve by eval id, so every existing pipeline span lands here
        transparently; the segment is shipped back on settle/submit
        and never enters the local ring.  A redelivered lease opens a
        fresh segment that supersedes the old one — same semantics as
        ``begin`` on the leader."""
        if not self.enabled or not eval_id or not ctx:
            return
        trace_id = ctx.get("trace_id") or ""
        if not trace_id:
            return
        segment = Trace(eval_id, 0, attrs)
        segment.trace_id = trace_id
        with self._lock:
            prior = self._segments.get(eval_id)
            if prior is not None and not prior.finished:
                prior.finish("superseded")
            self._segments[eval_id] = segment

    def export_segment(
        self,
        eval_id: str,
        server_id: str,
        close: bool = False,
        outcome: str = "shipped",
    ) -> Optional[Dict]:
        """Export the eval's segment spans closed since the last
        export; ``close=True`` (the settle RPC) also retires the local
        segment so the follower isn't left holding in-flight buffers
        for evals it no longer owns."""
        if not self.enabled:
            return None
        with self._lock:
            segment = self._segments.get(eval_id)
        if segment is None:
            return None
        # the ship itself is part of the record: a zero-duration mark
        # on the segment (and in this batch) shows when each export
        # left this server on the stitched waterfall
        segment.add_span(
            "fanout.remote_span_ship",
            time.monotonic(),
            0.0,
            {"server_id": server_id},
        )
        out = segment.export_segment(server_id)
        if close:
            with self._lock:
                if self._segments.get(eval_id) is segment:
                    del self._segments[eval_id]
            segment.finish(outcome)
        return out

    def absorb_segment(self, segment: Optional[Dict]) -> int:
        """Leader side: merge a shipped segment into the ring trace
        with the MATCHING full trace id.  Routing by trace id — not
        bare eval id — is what makes redelivery supersede across
        servers: a segment straggling in from a dead follower carries
        the old generation's trace id and lands in that (settled)
        trace, never interleaving into the redelivered attempt."""
        if not self.enabled or not segment:
            return 0
        trace_id = segment.get("trace_id") or ""
        if not trace_id:
            return 0
        eval_id = trace_id.rsplit("#", 1)[0]
        target = self._by_id.get(eval_id)
        if target is None or target.trace_id != trace_id:
            target = None
            with self._lock:
                candidates = list(self._ring)
            for trace in reversed(candidates):
                if trace.trace_id == trace_id:
                    target = trace
                    break
        if target is None:
            return 0
        absorbed = target.absorb_segment(segment)
        attrs = segment.get("attrs") or {}
        outcome = attrs.get("outcome")
        if outcome and not target.finished:
            # the follower's richer outcome annotation ("speculative",
            # "prescored", ...) travels in the segment attrs; a
            # successful ack consumes it in Trace.finish
            target.annotate({"outcome": outcome})
        return absorbed

    def open_segments(self) -> int:
        """Count of live follower-side recording segments."""
        with self._lock:
            return len(self._segments)

    # -- recording -----------------------------------------------------

    def _resolve(self, eval_id: str) -> Optional[Trace]:
        """Recording target for an eval: a live leased segment wins
        over the ring entry, but only while it is current — if the
        eval was re-begun locally under a NEW trace id (the lease was
        reclaimed and redelivered here), the stale segment is dropped
        rather than swallowing the new attempt's spans."""
        with self._lock:
            segment = self._segments.get(eval_id)
            if segment is not None:
                current = self._by_id.get(eval_id)
                if (
                    current is None
                    or current.trace_id == segment.trace_id
                ):
                    return segment
                del self._segments[eval_id]
        if segment is not None:
            segment.finish("superseded")
        return self._by_id.get(eval_id)

    def span(self, eval_id: str, name: str, **attrs):
        """Context manager timing a span on the eval's trace; no-op
        when tracing is off or the eval has no trace."""
        if not self.enabled:
            return _NULL
        trace = self._resolve(eval_id)
        if trace is None:
            return _NULL
        return _SpanCtx(trace, name, attrs)

    def add_span(
        self, eval_id: str, name: str, start: float,
        duration: float, **attrs,
    ) -> None:
        if not self.enabled:
            return
        trace = self._resolve(eval_id)
        if trace is not None:
            trace.add_span(name, start, duration, attrs)

    def event(self, eval_id: str, name: str, **attrs) -> None:
        if not self.enabled:
            return
        trace = self._resolve(eval_id)
        if trace is not None:
            trace.add_span(name, time.monotonic(), 0.0, attrs)

    def annotate(self, eval_id: str, **attrs) -> None:
        if not self.enabled:
            return
        trace = self._resolve(eval_id)
        if trace is not None:
            trace.annotate(attrs)

    # -- reads ---------------------------------------------------------

    def trace_id_of(self, eval_id: str) -> str:
        """Current trace id for an eval (newest generation), "" when
        untracked — the placement-explanation cross-link.  On a
        fan-out follower this resolves through the leased segment, so
        the link points at the leader's stitched trace."""
        trace = self._resolve(eval_id)
        return trace.trace_id if trace is not None else ""

    def get(self, ref: str) -> Optional[Dict]:
        """Resolve a bare eval id (newest generation) OR a full
        trace id (``<eval_id>#<gen>``, as listed by /v1/traces) —
        an id copied from the listing must dereference even after a
        redelivery superseded that generation."""
        trace = self._by_id.get(ref)
        if trace is not None:
            return trace.to_dict()
        if "#" in ref:
            with self._lock:
                candidates = list(self._ring)
            for trace in reversed(candidates):
                if trace.trace_id == ref:
                    return trace.to_dict()
        return None

    def recent(
        self,
        slow_ms: Optional[float] = None,
        outcome: Optional[str] = None,
        limit: int = 64,
        full: bool = False,
    ) -> List[Dict]:
        """Completed traces, newest first, optionally filtered to
        slow (>= slow_ms total) or outcome-matching ones."""
        with self._lock:
            candidates = list(self._ring)
        out: List[Dict] = []
        for trace in reversed(candidates):
            if not trace.finished:
                continue
            if outcome is not None and trace.outcome != outcome:
                continue
            if slow_ms is not None:
                dur = trace.duration_ms()
                if dur is None or dur < slow_ms:
                    continue
            out.append(trace.to_dict() if full else trace.summary())
            if len(out) >= limit:
                break
        return out

    def in_flight_ids(self, limit: int = 64) -> List[str]:
        """Eval ids with an open (unfinished) trace, newest first.

        The broadcast hook for cross-cutting marks: the overload
        ladder stamps ``overload.mode_change`` on every in-flight
        waterfall so the evals that RAN THROUGH a regime shift say
        so.  Bounded by ``limit`` — a broadcast must never turn a
        mode flip into an O(ring) stall."""
        if not self.enabled:
            return []
        with self._lock:
            candidates = list(self._ring)
        out: List[str] = []
        for trace in reversed(candidates):
            if trace.finished:
                continue
            out.append(trace.eval_id)
            if len(out) >= limit:
                break
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_id.clear()
            self._segments.clear()


TRACE = Tracer()

__all__ = [
    "MAX_SPANS",
    "SPAN_NAMES",
    "TRACE",
    "TRACE_RING",
    "Trace",
    "Tracer",
]
